"""Checkpoint integrity: checksums, corruption detection, fallback, orphan sweep.

Pins the contract of ``howto/fault_tolerance.md`` ("Checkpoint integrity and
retention"): a resume decision never rests on a torn or bit-rotted checkpoint.
"""

from __future__ import annotations

import pickle

import numpy as np
import pytest

from sheeprl_tpu.checkpoint.manager import (
    MANIFEST_FORMAT,
    CheckpointCorruptError,
    CheckpointManager,
)
from sheeprl_tpu.fault.chaos import corrupt_file
from sheeprl_tpu.fault.counters import fault_metrics


def _state(step: int) -> dict:
    rng = np.random.default_rng(step)
    return {
        "params": {"w": rng.standard_normal((4, 4)).astype(np.float32)},
        "policy_step": step,
    }


def _manager(tmp_path, **kw) -> CheckpointManager:
    return CheckpointManager(tmp_path / "checkpoints", **kw)


def test_save_writes_checksummed_manifest_and_verifies(tmp_path):
    manager = _manager(tmp_path)
    ckpt = manager.save(10, _state(10))
    with open(ckpt / "manifest.pkl", "rb") as f:
        manifest = pickle.load(f)
    assert manifest["format"] == MANIFEST_FORMAT
    assert "params.msgpack" in manifest["checksums"]
    assert "policy_step.pkl" in manifest["checksums"]
    assert CheckpointManager.verify(ckpt)
    state = CheckpointManager.load(ckpt, templates={"params": _state(10)["params"]})
    assert state["_step"] == 10
    np.testing.assert_array_equal(state["params"]["w"], _state(10)["params"]["w"])


def test_bitflip_fails_verify_and_load_without_fallback(tmp_path):
    manager = _manager(tmp_path)
    ckpt = manager.save(10, _state(10))
    corrupt_file(ckpt / "params.msgpack", mode="bitflip", seed=3)
    assert not CheckpointManager.verify(ckpt)
    with pytest.raises(CheckpointCorruptError, match="checksum mismatch"):
        CheckpointManager.load(ckpt, fallback=False)


def test_truncated_msgpack_falls_back_to_previous_valid(tmp_path, recwarn):
    manager = _manager(tmp_path)
    manager.save(10, _state(10))
    ckpt2 = manager.save(20, _state(20))
    corrupt_file(ckpt2 / "params.msgpack", mode="truncate")
    state = CheckpointManager.load(ckpt2, templates={"params": _state(10)["params"]})
    assert state["_step"] == 10
    np.testing.assert_array_equal(state["params"]["w"], _state(10)["params"]["w"])
    assert fault_metrics().get("Fault/checkpoint_fallbacks") == 1.0
    assert any("fell back" in str(w.message) for w in recwarn.list)


def test_missing_manifest_falls_back(tmp_path):
    manager = _manager(tmp_path)
    manager.save(10, _state(10))
    ckpt2 = manager.save(20, _state(20))
    (ckpt2 / "manifest.pkl").unlink()
    assert not CheckpointManager.verify(ckpt2)
    state = CheckpointManager.load(ckpt2)
    assert state["_step"] == 10


def test_corrupt_with_no_earlier_checkpoint_raises(tmp_path):
    manager = _manager(tmp_path)
    ckpt = manager.save(10, _state(10))
    corrupt_file(ckpt / "params.msgpack", mode="bitflip", seed=0)
    with pytest.raises(CheckpointCorruptError, match="no earlier valid checkpoint"):
        CheckpointManager.load(ckpt)


def test_latest_valid_skips_corrupt_newest(tmp_path):
    manager = _manager(tmp_path)
    ckpt1 = manager.save(10, _state(10))
    ckpt2 = manager.save(20, _state(20))
    assert CheckpointManager.latest_valid(manager.ckpt_dir) == ckpt2
    corrupt_file(ckpt2 / "params.msgpack", mode="bitflip", seed=0)
    assert CheckpointManager.latest_valid(manager.ckpt_dir) == ckpt1


def test_orphan_tmp_dirs_swept_at_init(tmp_path, recwarn):
    ckpt_dir = tmp_path / "checkpoints"
    ckpt_dir.mkdir(parents=True)
    orphan = ckpt_dir / ".tmp_ckpt_30"
    orphan.mkdir()
    (orphan / "params.msgpack").write_bytes(b"half-written garbage")
    manager = CheckpointManager(ckpt_dir)
    assert not orphan.exists()
    assert fault_metrics().get("Fault/orphan_tmp_swept") == 1.0
    assert any("orphaned .tmp_ckpt_" in str(w.message) for w in recwarn.list)
    # A published checkpoint is untouched by the sweep.
    ckpt = manager.save(10, _state(10))
    CheckpointManager(ckpt_dir)
    assert ckpt.exists() and CheckpointManager.verify(ckpt)


def test_legacy_format1_manifest_still_loads(tmp_path):
    """Pre-integrity checkpoints (no checksums) verify structurally and load."""
    manager = _manager(tmp_path)
    ckpt = manager.save(10, _state(10))
    with open(ckpt / "manifest.pkl", "rb") as f:
        manifest = pickle.load(f)
    legacy = {"step": manifest["step"], "entries": manifest["entries"]}
    with open(ckpt / "manifest.pkl", "wb") as f:
        pickle.dump(legacy, f)
    assert CheckpointManager.verify(ckpt)
    state = CheckpointManager.load(ckpt, templates={"params": _state(10)["params"]})
    assert state["_step"] == 10


def test_keep_last_retention(tmp_path):
    manager = _manager(tmp_path, keep_last=2)
    for step in (10, 20, 30):
        manager.save(step, _state(step))
    names = [p.name for p in manager.list_checkpoints()]
    assert names == ["ckpt_20", "ckpt_30"]
