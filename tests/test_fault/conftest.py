"""Shared state hygiene for the fault-tolerance tests.

The fault layer is deliberately process-global (sticky preemption flag,
counters, chaos worker-fault spec) — these fixtures guarantee no test leaks
that state into its neighbors.
"""

from __future__ import annotations

import pytest

from sheeprl_tpu.fault import chaos, counters, preemption


@pytest.fixture(autouse=True)
def _clean_fault_state():
    preemption.clear_preemption()
    counters.reset()
    chaos.install({})
    yield
    preemption.clear_preemption()
    counters.reset()
    chaos.install({})
