"""End-to-end chaos drills (slow tier; the fast-tier equivalent runs as a CI
workflow step, see ``cpu-tests.yaml`` "Chaos preemption + autoresume smoke").

The acceptance contract: SIGTERM mid-run + autoresume reaches final params
BIT-IDENTICAL to an uninterrupted run, and a bit-flipped latest checkpoint
resumes from the previous valid one instead of deserializing garbage.
"""

from __future__ import annotations

from pathlib import Path

import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.fault.chaos import corrupt_file
from tests.test_algos.test_anakin import PPO_ANAKIN_ARGS, SAC_ANAKIN_ARGS

pytestmark = pytest.mark.slow

# Mirrors the CI workflow smoke ("Chaos preemption + autoresume smoke"): a tiny
# deterministic run with checkpoints every 16 of 64 total policy steps.
_E2E = [
    "algo.total_steps=64",
    "env.num_envs=2",
    "env.capture_video=False",
    "mesh.devices=1",
    "checkpoint.every=16",
    "checkpoint.save_last=True",
    "metric.log_every=16",
    "buffer.memmap=False",
    "algo.run_test=False",
]


def _final_carry(root: Path) -> Path:
    """The highest-step (then newest) ``carry.msgpack`` under a run tree."""
    candidates = sorted(
        root.rglob("ckpt_*/carry.msgpack"),
        key=lambda p: (int(p.parent.name.split("_")[1]), p.stat().st_mtime),
    )
    assert candidates, f"no checkpoints under {root}"
    return candidates[-1]


def _args(base, tmp_path, sub, extra=()):
    return base + _E2E + [f"log_root={tmp_path / sub}"] + list(extra)


def test_ppo_anakin_kill_autoresume_bit_identical(tmp_path):
    run(_args(PPO_ANAKIN_ARGS, tmp_path, "killed", ["chaos.kill_at_step=32", "fault.autoresume=True"]))
    run(_args(PPO_ANAKIN_ARGS, tmp_path, "clean"))
    killed = _final_carry(tmp_path / "killed")
    clean = _final_carry(tmp_path / "clean")
    assert int(killed.parent.name.split("_")[1]) == 64
    assert killed.read_bytes() == clean.read_bytes(), (
        "kill-at-32 + autoresume diverged from the uninterrupted run"
    )
    # the interrupted attempt left its PREEMPTED marker behind
    assert list((tmp_path / "killed").rglob("PREEMPTED")), "no PREEMPTED marker written"


def test_sac_anakin_kill_autoresume_bit_identical(tmp_path):
    extra = ["chaos.kill_at_step=32", "fault.autoresume=True"]
    run(_args(SAC_ANAKIN_ARGS, tmp_path, "killed", extra))
    run(_args(SAC_ANAKIN_ARGS, tmp_path, "clean"))
    killed = _final_carry(tmp_path / "killed")
    clean = _final_carry(tmp_path / "clean")
    assert killed.read_bytes() == clean.read_bytes(), (
        "SAC kill-at-32 + autoresume diverged from the uninterrupted run"
    )


def test_ppo_anakin_resume_falls_back_past_bitflipped_checkpoint(tmp_path):
    run(_args(PPO_ANAKIN_ARGS, tmp_path, "run"))
    latest = _final_carry(tmp_path / "run").parent
    assert latest.name == "ckpt_64"
    corrupt_file(latest / "carry.msgpack", mode="bitflip", seed=0)
    # Resuming from the damaged checkpoint must fall back to ckpt_48 and finish.
    run(_args(PPO_ANAKIN_ARGS, tmp_path, "run", [f"checkpoint.resume_from={latest}"]))
