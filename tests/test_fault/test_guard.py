"""TrainingGuard: the safe-boundary hook every training loop calls."""

from __future__ import annotations

import numpy as np
import pytest

from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault import preemption
from sheeprl_tpu.fault.counters import fault_metrics
from sheeprl_tpu.fault.guard import TrainingGuard


def _cfg(**chaos) -> dict:
    return {"chaos": chaos, "checkpoint": {}, "fault": {}}


def test_boundary_is_noop_without_flag_or_schedule(tmp_path):
    guard = TrainingGuard(_cfg(), str(tmp_path))
    guard.boundary(100, lambda: (_ for _ in ()).throw(AssertionError("must not save")))


def test_boundary_preempts_saves_and_writes_marker(tmp_path):
    guard = TrainingGuard(_cfg(), str(tmp_path))
    manager = CheckpointManager(tmp_path / "checkpoints")
    saved = []

    def save_ckpt():
        path = manager.save(64, {"params": {"w": np.zeros(3, np.float32)}, "policy_step": 64})
        saved.append(path)
        return path

    preemption.request_preemption("SIGTERM")
    with pytest.raises(preemption.Preempted) as exc_info:
        guard.boundary(64, save_ckpt)
    assert saved, "the boundary must cut the goodbye checkpoint"
    assert exc_info.value.step == 64
    assert exc_info.value.ckpt_path == str(saved[0])
    marker = preemption.read_marker(tmp_path)
    assert marker is not None and marker["step"] == 64
    assert marker["resume_from"] == str(saved[0])
    assert fault_metrics().get("Fault/preemptions") == 1.0


def test_boundary_save_failure_falls_back_to_latest_valid(tmp_path, recwarn):
    """A failed goodbye checkpoint must not mask the graceful exit: the marker
    points at the newest valid checkpoint already on disk."""
    manager = CheckpointManager(tmp_path / "checkpoints")
    existing = manager.save(32, {"params": {"w": np.zeros(3, np.float32)}})
    guard = TrainingGuard(_cfg(), str(tmp_path))

    def failing_save():
        raise OSError("disk full")

    preemption.request_preemption("SIGTERM")
    with pytest.raises(preemption.Preempted) as exc_info:
        guard.boundary(40, failing_save)
    assert exc_info.value.ckpt_path == str(existing)
    assert any("preemption checkpoint" in str(w.message) for w in recwarn.list)
    marker = preemption.read_marker(tmp_path)
    assert marker["resume_from"] == str(existing)
