"""Failure classification matrix and the supervisor's pure helpers."""

from __future__ import annotations

import json

import numpy as np
import pytest

from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault import classify
from sheeprl_tpu.fault.chaos import corrupt_file
from sheeprl_tpu.fault.preemption import RESUMABLE_EXIT_CODE, Preempted
from sheeprl_tpu.fault.supervisor import (
    _strip_override,
    backoff_seconds,
    find_resume_checkpoint,
)
from sheeprl_tpu.analysis.strict import NonFiniteError


# ------------------------------------------------------------ classification
def test_classify_exception_matrix():
    assert classify.classify_exception(Preempted(5)) == classify.RESUME
    assert classify.classify_exception(NonFiniteError("loss is NaN")) == classify.FATAL
    assert classify.classify_exception(KeyboardInterrupt()) == classify.FATAL
    assert classify.classify_exception(ValueError("flaky")) == classify.RETRY
    assert classify.classify_exception(OSError("stale NFS handle")) == classify.RETRY


def test_classify_exit_matrix():
    assert classify.classify_exit(0) == classify.DONE
    assert classify.classify_exit(RESUMABLE_EXIT_CODE) == classify.RESUME
    assert classify.classify_exit(1) == classify.RETRY
    assert classify.classify_exit(-9) == classify.RETRY  # SIGKILL: transient
    fatal_meta = {"exception": {"type": "NonFiniteError"}}
    assert classify.classify_exit(1, fatal_meta) == classify.FATAL
    retry_meta = {"exception": {"type": "RuntimeError"}}
    assert classify.classify_exit(1, retry_meta) == classify.RETRY


def test_read_blackbox_meta_picks_newest_and_survives_garbage(tmp_path):
    assert classify.read_blackbox_meta(tmp_path) is None
    old = tmp_path / "version_0" / "blackbox"
    new = tmp_path / "version_1" / "blackbox"
    old.mkdir(parents=True)
    new.mkdir(parents=True)
    (old / "meta.json").write_text(json.dumps({"exception": {"type": "Old"}}))
    (new / "meta.json").write_text(json.dumps({"exception": {"type": "New"}}))
    import os
    os.utime(old / "meta.json", (1, 1))
    meta = classify.read_blackbox_meta(tmp_path)
    assert meta["exception"]["type"] == "New"
    (new / "meta.json").write_text("not json{")
    meta = classify.read_blackbox_meta(tmp_path)
    assert meta["exception"]["type"] == "Old"


# ----------------------------------------------------------------- backoff
def test_backoff_doubles_and_caps():
    assert backoff_seconds(1, 2.0, 60.0) == 2.0
    assert backoff_seconds(2, 2.0, 60.0) == 4.0
    assert backoff_seconds(3, 2.0, 60.0) == 8.0
    assert backoff_seconds(10, 2.0, 60.0) == 60.0


def test_strip_override():
    kept, value = _strip_override(["a=1", "fault.autoresume=True", "b=2"], "fault.autoresume")
    assert kept == ["a=1", "b=2"]
    assert value == "True"
    kept, value = _strip_override(["a=1"], "run_name")
    assert kept == ["a=1"] and value is None


# -------------------------------------------------- resume-ckpt discovery
def _publish(run_dir, version: int, step: int):
    manager = CheckpointManager(run_dir / f"version_{version}" / "checkpoints")
    return manager.save(step, {"params": {"w": np.zeros(4, np.float32)}})


def test_find_resume_checkpoint_newest_step_across_versions(tmp_path):
    assert find_resume_checkpoint(tmp_path) is None
    _publish(tmp_path, 0, 10)
    _publish(tmp_path, 0, 20)
    newest = _publish(tmp_path, 1, 30)
    assert find_resume_checkpoint(tmp_path) == newest


def test_find_resume_checkpoint_skips_corrupt_newest(tmp_path):
    older = _publish(tmp_path, 0, 20)
    newest = _publish(tmp_path, 1, 30)
    corrupt_file(newest / "params.msgpack", mode="truncate")
    assert find_resume_checkpoint(tmp_path) == older
