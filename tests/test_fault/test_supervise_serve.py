"""The serve-mode relaunch loop (``supervise --serve``): crash-retry vs
clean-preemption accounting, backoff reset on a healthy drain, and the
lifetime summary JSON on every exit path.  The child is a stub — no replica
process is ever spawned."""

import json
import subprocess
import time
from types import SimpleNamespace

import pytest

from sheeprl_tpu.fault.counters import RESTARTS_ENV_VAR
from sheeprl_tpu.fault.supervisor import SUPERVISE_SUMMARY_ENV_VAR, supervise_serve


@pytest.fixture
def loop(tmp_path, monkeypatch):
    """Run supervise_serve against a scripted sequence of child exit codes,
    capturing backoff sleeps and the env each attempt was launched with."""
    monkeypatch.delenv(SUPERVISE_SUMMARY_ENV_VAR, raising=False)
    summary_path = tmp_path / "summary.json"
    calls = SimpleNamespace(sleeps=[], restarts=[], argvs=[])

    def run(rcs, extra=()):
        seq = iter(rcs)

        def fake_run(argv, env=None, **kwargs):
            calls.argvs.append(argv)
            calls.restarts.append(env[RESTARTS_ENV_VAR])
            return SimpleNamespace(returncode=next(seq))

        monkeypatch.setattr(subprocess, "run", fake_run)
        monkeypatch.setattr(time, "sleep", lambda s: calls.sleeps.append(s))
        rc = supervise_serve(
            [
                f"fault.summary_path={summary_path}",
                "fault.max_retries=3",
                "fault.backoff_s=2.0",
                "fault.backoff_max_s=60.0",
                *extra,
            ]
        )
        return rc, json.loads(summary_path.read_text())

    return run, calls


def test_preemption_is_not_a_crash_and_resets_the_backoff(loop):
    run, calls = loop
    # crash, drained preemption, crash, crash, clean shutdown
    rc, summary = run([1, 75, 1, 1, 0])
    assert rc == 0
    # the preemption respawned with NO sleep, and reset the consecutive-crash
    # clock: the post-preemption crashes back off from the base again
    assert calls.sleeps == [2.0, 2.0, 4.0]
    assert summary["outcome"] == "clean" and summary["rc"] == 0
    assert summary["attempts"] == 5
    assert summary["retries"] == 3  # total crashes, separate from...
    assert summary["preemptions"] == 1  # ...clean preemptions
    assert [e["kind"] for e in summary["events"]] == [
        "crash", "preemption", "crash", "crash",
    ]
    # every attempt told the child its lineage position
    assert calls.restarts == ["0", "1", "2", "3", "4"]


def test_retry_budget_exhaustion_writes_the_summary(loop):
    run, calls = loop
    rc, summary = run([2, 2, 2, 2], extra=("fault.max_retries=3",))
    assert rc == 2
    assert summary["outcome"] == "retry_budget" and summary["rc"] == 2
    assert summary["retries"] == 4 and summary["preemptions"] == 0
    assert calls.sleeps == [2.0, 4.0, 8.0]  # the final crash exits, no sleep


def test_preemption_budget_bounds_eternal_respawns(loop):
    run, calls = loop
    rc, summary = run([75, 75], extra=("fault.max_preemptions=1",))
    assert rc == 75
    assert summary["outcome"] == "preemption_budget"
    assert summary["preemptions"] == 2 and summary["retries"] == 0
    assert calls.sleeps == []  # preemptions never back off
