"""Chaos harness: schedule grammar, edge triggers, fault effects."""

from __future__ import annotations

import numpy as np
import pytest

from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault import chaos, preemption
from sheeprl_tpu.fault.chaos import ChaosMonkey, corrupt_file
from sheeprl_tpu.fault.counters import fault_metrics
from sheeprl_tpu.rollout import EnvPool
from sheeprl_tpu.envs.dummy import DiscreteDummyEnv


def _cfg(**kw) -> dict:
    return {"chaos": kw}


# ----------------------------------------------------------------- grammar
def test_install_rejects_bad_kill_signal():
    with pytest.raises(ValueError, match="chaos.kill_signal must be one of"):
        chaos.install(_cfg(kill_at_step=5, kill_signal="SIGSTOP"))


def test_install_rejects_bad_corrupt_mode():
    with pytest.raises(ValueError, match="chaos.corrupt_mode must be one of"):
        chaos.install(_cfg(corrupt_ckpt_at_step=5, corrupt_mode="shred"))


def test_install_rejects_bad_worker_fault_mode():
    with pytest.raises(ValueError, match="chaos.worker_fault_mode must be one of"):
        chaos.install(_cfg(worker_fault_at_step=5, worker_fault_mode="explode"))


# ------------------------------------------------------------- edge trigger
def test_disabled_monkey_is_inert():
    monkey = ChaosMonkey(_cfg())
    assert not monkey.enabled
    monkey.fire(10**9)


def test_delay_fires_exactly_once_on_crossing(monkeypatch):
    sleeps = []
    monkeypatch.setattr(chaos.time, "sleep", sleeps.append)
    monkey = ChaosMonkey(_cfg(delay_at_step=10, delay_ms=250))
    monkey.fire(5)
    assert not sleeps
    monkey.fire(12)  # crosses the threshold
    assert sleeps == [0.25]
    monkey.fire(20)  # edge trigger: never again
    assert sleeps == [0.25]
    assert fault_metrics().get("Fault/chaos_injected") == 1.0


def test_resumed_run_past_threshold_never_fires(monkeypatch):
    """A run resumed past the threshold crossed it in a previous life — the
    fault is marked fired without firing (kill + autoresume terminates)."""
    sleeps = []
    monkeypatch.setattr(chaos.time, "sleep", sleeps.append)
    monkey = ChaosMonkey(_cfg(delay_at_step=10, delay_ms=250), resumed=True)
    monkey.fire(32)  # first boundary of the resumed run, already past 10
    monkey.fire(48)
    assert not sleeps


def test_fresh_run_past_threshold_does_fire(monkeypatch):
    sleeps = []
    monkeypatch.setattr(chaos.time, "sleep", sleeps.append)
    monkey = ChaosMonkey(_cfg(delay_at_step=10, delay_ms=250), resumed=False)
    monkey.fire(32)
    assert sleeps == [0.25]


def test_kill_sigterm_sets_sticky_preemption_flag():
    """The SIGTERM kill waits for the sticky flag so the same boundary that
    fired the fault handles the graceful shutdown."""
    assert preemption.install_signal_handlers()
    monkey = ChaosMonkey(_cfg(kill_at_step=4, kill_signal="SIGTERM"))
    monkey.fire(4)
    assert preemption.preemption_requested()
    assert preemption.signal_name() == "SIGTERM"


def test_corrupt_latest_invalidates_newest_checkpoint(tmp_path):
    manager = CheckpointManager(tmp_path / "checkpoints")
    state = {"params": {"w": np.zeros((4, 4), np.float32)}}
    ckpt1 = manager.save(10, state)
    ckpt2 = manager.save(20, state)
    monkey = ChaosMonkey(_cfg(corrupt_ckpt_at_step=15), ckpt_dir=manager.ckpt_dir)
    monkey.fire(20)
    assert not CheckpointManager.verify(ckpt2)
    assert CheckpointManager.latest_valid(manager.ckpt_dir) == ckpt1


# ------------------------------------------------------------- corrupt_file
def test_corrupt_file_bitflip_is_deterministic(tmp_path):
    a = tmp_path / "a.bin"
    b = tmp_path / "b.bin"
    payload = bytes(range(256))
    a.write_bytes(payload)
    b.write_bytes(payload)
    corrupt_file(a, mode="bitflip", seed=7)
    corrupt_file(b, mode="bitflip", seed=7)
    assert a.read_bytes() == b.read_bytes() != payload
    # exactly one byte differs, by exactly one bit
    diff = [(x, y) for x, y in zip(a.read_bytes(), payload) if x != y]
    assert len(diff) == 1 and diff[0][0] ^ diff[0][1] == 0x01


def test_corrupt_file_truncate_halves(tmp_path):
    f = tmp_path / "f.bin"
    f.write_bytes(b"x" * 100)
    corrupt_file(f, mode="truncate")
    assert f.stat().st_size == 50


# ------------------------------------------------------------ worker faults
def test_maybe_worker_fault_is_noop_for_other_slots_and_generations():
    chaos.install(_cfg(worker_fault_at_step=1, worker_fault_mode="crash", worker_index=0))
    # Wrong worker / wrong generation / wrong step: none of these may os._exit.
    chaos.maybe_worker_fault(worker_idx=1, generation=0, step_count=1)
    chaos.maybe_worker_fault(worker_idx=0, generation=1, step_count=1)
    chaos.maybe_worker_fault(worker_idx=0, generation=0, step_count=2)


def test_worker_crash_fault_rides_fork_and_pool_restarts(recwarn):
    """The spec installed in the parent before the fork crashes worker 0 at its
    2nd step command; the pool restarts it and the replacement (generation 1)
    runs clean."""
    chaos.install(_cfg(worker_fault_at_step=2, worker_fault_mode="crash", worker_index=0))
    try:
        thunks = [lambda: DiscreteDummyEnv(n_steps=32)]
        pool = EnvPool(thunks, num_workers=1, step_timeout_s=30.0, max_restarts=2, restart_backoff_s=0.0)
        try:
            pool.reset(seed=0)
            pool.step(np.zeros(1, np.int64))
            obs, rew, term, trunc, info = pool.step(np.zeros(1, np.int64))  # chaos crash
            assert trunc[0] and info["rollout_restart"][0]
            m = pool.rollout_metrics()
            assert m["Rollout/worker_restarts"] == 1.0
            assert m["Rollout/worker_crashes"] == 1.0
            # generation 1 is immune: stepping continues
            pool.step(np.zeros(1, np.int64))
        finally:
            pool.close(terminate=True)
    finally:
        chaos.install({})
