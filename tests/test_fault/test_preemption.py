"""Preemption signal layer: sticky flag, real-signal delivery, marker file."""

from __future__ import annotations

import os
import signal
import time

from sheeprl_tpu.fault import preemption
from sheeprl_tpu.fault.counters import fault_metrics


def test_request_and_clear_preemption():
    assert not preemption.preemption_requested()
    preemption.request_preemption("test")
    assert preemption.preemption_requested()
    assert preemption.signal_name() == "test"
    preemption.clear_preemption()
    assert not preemption.preemption_requested()
    assert preemption.signal_name() is None


def test_real_sigterm_sets_sticky_flag_only():
    """The handler does no work in signal context: one SIGTERM just sets the
    flag (and bumps the counter) — the boundary does the rest."""
    assert preemption.install_signal_handlers()
    os.kill(os.getpid(), signal.SIGTERM)
    deadline = time.monotonic() + 5.0
    while not preemption.preemption_requested() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert preemption.preemption_requested()
    assert preemption.signal_name() == "SIGTERM"
    assert fault_metrics().get("Fault/preemption_signals") == 1.0


def test_preempted_exception_carries_resume_context():
    exc = preemption.Preempted(42, log_dir="/tmp/run", ckpt_path="/tmp/run/ckpt_42")
    assert exc.step == 42
    assert exc.log_dir == "/tmp/run"
    assert exc.ckpt_path == "/tmp/run/ckpt_42"
    assert "42" in str(exc)


def test_marker_round_trip(tmp_path):
    preemption.request_preemption("SIGTERM")
    path = preemption.write_marker(tmp_path, 128, resume_from=str(tmp_path / "ckpt_128"))
    assert path is not None and path.name == preemption.PREEMPTED_MARKER
    marker = preemption.read_marker(tmp_path)
    assert marker["step"] == 128
    assert marker["resume_from"].endswith("ckpt_128")
    assert marker["signal"] == "SIGTERM"
    preemption.clear_marker(tmp_path)
    assert preemption.read_marker(tmp_path) is None


def test_read_marker_absent_or_garbage(tmp_path):
    assert preemption.read_marker(tmp_path) is None
    (tmp_path / preemption.PREEMPTED_MARKER).write_text("not json{")
    assert preemption.read_marker(tmp_path) is None
