"""Test harness: force an 8-device virtual CPU mesh before JAX initialises.

Mirrors the reference's multi-device CI trick (LT_DEVICES with gloo on localhost,
``tests/test_algos/test_algos.py:16-18``) using
``--xla_force_host_platform_device_count`` per SURVEY §4's TPU-build implication.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

# The image's sitecustomize registers the TPU plugin and sets jax_platforms at
# interpreter start (before this file runs); backends initialise lazily, so
# overriding the config here still lands before any device is created.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _race_detector_session():
    """jaxlint-threads runtime half under pytest: when the CI job exports
    ``SHEEPRL_TPU_RACE_DETECT=1`` (the concurrency suites — test_distributed /
    test_serve / test_obs — run once this way), every lock the tests create is
    instrumented; the session ends by dumping the JSONL race report into
    ``$SHEEPRL_TPU_RACE_DIR`` (default: the launch directory) where the CI step
    asserts zero lock-order cycles.  A no-op without the env var."""
    if os.environ.get("SHEEPRL_TPU_RACE_DETECT", "0") in ("", "0"):
        yield
        return
    from sheeprl_tpu.analysis.threads import runtime as race_runtime

    detector = race_runtime.RaceDetector(
        log_dir=os.environ.get("SHEEPRL_TPU_RACE_DIR") or os.getcwd(),
        held_threshold_ms=float(os.environ.get("SHEEPRL_TPU_RACE_HOLD_MS", "500")),
    )
    race_runtime.install(detector)
    try:
        yield
    finally:
        race_runtime.uninstall()
        path = detector.dump("pytest-session")
        counts = detector.counts()
        print(f"\nrace detector: {counts} -> {path}")


@pytest.fixture()
def tmp_logs(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


@pytest.fixture(autouse=True)
def no_env_var_leaks():
    """Reference test strategy (``tests/conftest.py:26-60``): a test that mutates the
    framework's environment knobs without cleaning up poisons every test after it —
    fail loudly on the offender instead.  Scoped to the prefixes the framework reads
    (libraries set unrelated vars as import side effects; that's not a leak), minus
    the keys the harness itself manages."""
    exempt = {"XLA_FLAGS", "JAX_PLATFORMS", "SHEEPRL_TPU_QUIET"}
    prefixes = ("SHEEPRL", "MLFLOW", "JAX_", "XLA_")

    def snapshot():
        return {
            k: v
            for k, v in os.environ.items()
            if k.startswith(prefixes) and k not in exempt
        }

    before = snapshot()
    yield
    after = snapshot()
    added = set(after) - set(before)
    removed = set(before) - set(after)
    changed = {k for k in set(before) & set(after) if before[k] != after[k]}
    assert not (added or removed or changed), (
        f"test leaked environment variables: added={sorted(added)} "
        f"removed={sorted(removed)} changed={sorted(changed)}"
    )
