"""One positive and one negative fixture per jaxlint rule (JL001–JL006)."""

import textwrap

import pytest

from sheeprl_tpu.analysis.engine import run_lint
from sheeprl_tpu.analysis.rules import default_rules
from tests.test_analysis.conftest import rule_ids


# ------------------------------------------------------------------------- JL001
def test_jl001_positive_reuse(lint):
    findings = lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a + b
        """
    )
    assert "JL001" in rule_ids(findings)


def test_jl001_positive_loop_carried(lint):
    findings = lint(
        """
        import jax

        def f(key, xs):
            out = []
            for x in xs:
                out.append(jax.random.normal(key, (3,)))
            return out
        """
    )
    assert "JL001" in rule_ids(findings)


def test_jl001_negative_split(lint):
    findings = lint(
        """
        import jax

        def f(key):
            key, k1 = jax.random.split(key)
            a = jax.random.normal(k1, (3,))
            key, k2 = jax.random.split(key)
            b = jax.random.uniform(k2, (3,))
            return a + b

        def loop(key, xs):
            for x in xs:
                key, sub = jax.random.split(key)
                x = jax.random.normal(sub, (3,))
            return x
        """
    )
    assert "JL001" not in rule_ids(findings)


def test_jl001_negative_exclusive_branches(lint):
    # the dreamer pattern: both branches consume the key, but only one runs
    findings = lint(
        """
        import jax

        def f(key, flag):
            if flag:
                ks = jax.random.split(key, 3)
            else:
                ks = jax.random.split(key, 5)
            return ks

        def g(key, cont):
            if cont:
                return jax.random.normal(key, (2,))
            k1, k2 = jax.random.split(key)
            return jax.random.uniform(k1, (2,))
        """
    )
    assert "JL001" not in rule_ids(findings)


# ------------------------------------------------------------------------- JL002
def test_jl002_positive_if_and_while(lint):
    findings = lint(
        """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            while x < 10:
                x = x + 1
            return x
        """
    )
    assert rule_ids(findings).count("JL002") == 2


def test_jl002_positive_scan_body(lint):
    findings = lint(
        """
        import jax

        def outer(xs):
            def body(carry, x):
                if x > 0:
                    carry = carry + x
                return carry, x
            return jax.lax.scan(body, 0.0, xs)
        """
    )
    assert "JL002" in rule_ids(findings)


def test_jl002_negative_static_conditions(lint):
    findings = lint(
        """
        import jax

        @jax.jit
        def f(x, flag_from_closure):
            cfg_flag = True
            if cfg_flag:
                x = x * 2
            if x.shape[0] == 3:
                x = x + 1
            if len(x.shape) > 1:
                x = x.sum()
            y = jax.numpy.where(x > 0, x, -x)
            return y
        """
    )
    assert "JL002" not in rule_ids(findings)


def test_jl002_negative_autoreset_cond_select(lint):
    """The jax-env auto-reset idiom (``envs/jax/core.py``) is the JL002-CLEAN way
    to branch on a traced ``done``: both branches computed, merged with
    ``lax.select`` over the state tree (or ``lax.cond`` for whole-branch
    dispatch) — no python ``if`` ever touches the traced flag.  Pinned here so
    the pattern stays lint-clean as the rule evolves."""
    findings = lint(
        """
        import jax

        def step_autoreset(params, state, action, key):
            key_step, key_reset = jax.random.split(key)
            stepped, obs_st, reward, done, info = env_step(params, state, action, key_step)
            reset_state, reset_obs = env_reset(params, key_reset)
            state = jax.tree.map(lambda r, s: jax.lax.select(done, r, s), reset_state, stepped)
            obs = jax.lax.cond(done, lambda _: reset_obs, lambda _: obs_st, None)
            return state, obs, reward, done, info
        """
    )
    assert "JL002" not in rule_ids(findings)


# ------------------------------------------------------------------------- JL003
def test_jl003_positive_host_sync_in_loop(lint):
    findings = lint(
        """
        import jax
        import numpy as np

        def train(step, data):
            step = jax.jit(step)
            total = 0.0
            for batch in data:
                loss = step(batch)
                total += float(loss)
                _ = loss.item()
                _ = np.asarray(loss)
            return total
        """
    )
    assert rule_ids(findings).count("JL003") == 3


def test_jl003_negative_explicit_sync_and_host_values(lint):
    findings = lint(
        """
        import jax
        import numpy as np

        def train(step, data, env):
            step = jax.jit(step)
            for batch in data:
                loss = step(batch)
                host = jax.device_get(loss)      # explicit, deliberate sync
                total = float(host)
                obs, reward = env.step(np.ones(3))  # host values from the env
                r = float(reward)
            out = step(data[0])
            final = float(out)                    # outside the loop: fine
            return total, r, final
        """
    )
    assert "JL003" not in rule_ids(findings)


# ------------------------------------------------------------------------- JL004
def test_jl004_positive_jit_in_loop(lint):
    findings = lint(
        """
        import jax

        def f(fns, x):
            for fn in fns:
                g = jax.jit(fn)
                x = g(x)
            return x
        """
    )
    assert "JL004" in rule_ids(findings)


def test_jl004_positive_varying_static_arg(lint):
    findings = lint(
        """
        import jax

        def f(x):
            g = jax.jit(lambda a, n: a * n, static_argnums=(1,))
            for n in range(10):
                x = g(x, n)
            return x
        """
    )
    assert any(f.rule == "JL004" and "static" in f.message for f in findings)


def test_jl004_positive_mutable_closure(lint):
    findings = lint(
        """
        import jax

        def train(x, steps):
            params = init()

            @jax.jit
            def step(v):
                return params @ v

            for _ in range(steps):
                params = update(params)
                x = step(x)
            return x
        """
    )
    assert any(f.rule == "JL004" and "closes over" in f.message for f in findings)


def test_jl004_negative_hoisted_jit(lint):
    findings = lint(
        """
        import jax

        def f(fn, xs):
            g = jax.jit(fn, static_argnums=(1,))
            n = 4
            out = []
            for x in xs:
                out.append(g(x, n))
            return out
        """
    )
    assert "JL004" not in rule_ids(findings)


# ------------------------------------------------------------------------- JL005
def test_jl005_positive_use_after_donation(lint):
    findings = lint(
        """
        import jax

        def f(params, batch):
            step = jax.jit(train, donate_argnums=(0,))
            new_params = step(params, batch)
            return params + new_params
        """
    )
    assert "JL005" in rule_ids(findings)


def test_jl005_positive_loop_without_rebind(lint):
    findings = lint(
        """
        import jax

        def f(params, batches):
            step = jax.jit(train, donate_argnums=(0,))
            outs = []
            for b in batches:
                outs.append(step(params, b))
            return outs
        """
    )
    assert "JL005" in rule_ids(findings)


def test_jl005_negative_rebound(lint):
    findings = lint(
        """
        import jax

        def f(params, batches):
            step = jax.jit(train, donate_argnums=(0,))
            for b in batches:
                params = step(params, b)
            return params
        """
    )
    assert "JL005" not in rule_ids(findings)


# ------------------------------------------------------------------------- JL006
@pytest.fixture()
def config_tree(tmp_path):
    cfg = tmp_path / "configs"
    (cfg / "algo").mkdir(parents=True)
    (cfg / "config.yaml").write_text("defaults:\n  - algo: tuned\nseed: 42\nunused_root: 1\n")
    (cfg / "algo" / "tuned.yaml").write_text("name: tuned\noptimizer:\n  lr: 1e-3\n")
    return cfg


def _lint_jl006(tmp_path, config_tree, source):
    mod = tmp_path / "snippet.py"
    mod.write_text(textwrap.dedent(source))
    return run_lint([mod], rules=default_rules(["JL006"]), config_dir=config_tree, root=tmp_path)


def test_jl006_positive_undefined_and_unused(tmp_path, config_tree):
    findings = _lint_jl006(
        tmp_path,
        config_tree,
        """
        def main(cfg):
            lr = cfg.algo.optimizer.get("lr", 1e-3)
            eps = cfg.algo.optimizer.get("eps", 1e-8)   # not in YAML -> drift
            return lr, eps
        """,
    )
    details = {f.detail for f in findings}
    assert "undefined:algo.optimizer.eps" in details
    assert "unused:unused_root" in details  # defined in YAML, never read
    assert "undefined:algo.optimizer.lr" not in details


def test_jl006_negative_all_defined_and_used(tmp_path, config_tree):
    findings = _lint_jl006(
        tmp_path,
        config_tree,
        """
        def main(cfg):
            s = cfg.seed
            u = cfg.get("unused_root")
            name = cfg.algo.name
            return s, u, name, cfg.algo.optimizer.lr
        """,
    )
    assert findings == []


def test_jl006_param_propagation(tmp_path, config_tree):
    # make_optimizer-style: the sub-config access happens in a helper
    findings = _lint_jl006(
        tmp_path,
        config_tree,
        """
        def make_opt(opt_cfg):
            return opt_cfg.get("lr"), opt_cfg.get("weight_decay", 0.0)

        def main(cfg):
            _ = cfg.seed, cfg.algo.name, cfg.unused_root
            return make_opt(cfg.algo.optimizer)
        """,
    )
    assert "undefined:algo.optimizer.weight_decay" in {f.detail for f in findings}


def test_jl006_local_alias_resolution(tmp_path, config_tree):
    findings = _lint_jl006(
        tmp_path,
        config_tree,
        """
        def main(cfg):
            _ = cfg.seed, cfg.unused_root
            opt = cfg.algo.optimizer
            return opt.lr, opt.get("typo_key"), cfg.algo.name
        """,
    )
    assert "undefined:algo.optimizer.typo_key" in {f.detail for f in findings}


# ---------------------------------------------------------------------- JL007
def test_jl007_positive_caller_reuse_through_wrapper(lint):
    findings = lint(
        """
        import jax

        block = jax.jit(lambda c: c, donate_argnums=(0,))

        def run(carry):
            return block(carry)

        def loop(carry):
            out = run(carry)
            print(carry)  # the wrapper donated it
            return out
        """,
        select=["JL007"],
    )
    assert rule_ids(findings) == ["JL007"]
    assert "carry" in findings[0].message


def test_jl007_positive_method_wrapper_shifts_self(lint):
    findings = lint(
        """
        import jax

        class Dispatcher:
            def __init__(self):
                self._block = jax.jit(lambda c: c, donate_argnums=(0,))

            def dispatch(self, carry, n):
                block = jax.jit(lambda c: c, donate_argnums=(0,))
                return block(carry)

        def loop(d, carry):
            new = d.dispatch(carry, 3)
            return carry  # donated through the method's first real argument
        """,
        select=["JL007"],
    )
    assert rule_ids(findings) == ["JL007"]


def test_jl007_negative_rebound_and_copied(lint):
    findings = lint(
        """
        import jax
        import jax.numpy as jnp

        block = jax.jit(lambda c: c, donate_argnums=(0,))

        def run(carry):
            return block(carry)

        def good_loop(carry):
            carry = run(carry)  # rebound: the new buffer is valid
            return carry

        def defensive(carry):
            carry = jax.tree.map(jnp.copy, carry)
            return block(carry)

        def caller(carry):
            out = defensive(carry)
            return carry  # defensive copied before donating: caller binding safe
        """,
        select=["JL007"],
    )
    assert findings == []
