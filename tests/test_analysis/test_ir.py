"""jaxlint-IR: the jaxpr/HLO audit tier (``sheeprl_tpu/analysis/ir``).

Rule-level tests build tiny synthetic jitted programs; the CLI tests inject REAL
violations — an un-donated buffer (IR001) and a compile-memory budget inflation
(IR006) — through a monkeypatched registry and assert the non-zero exit the CI
job relies on.  The audit of the actual entry points runs in ``test_e2e.py``
(one cheap entry in tier 1, the full registry as a slow test + the CI job).
"""

from __future__ import annotations

import json
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.analysis.ir import budgets as budgets_mod
from sheeprl_tpu.analysis.ir import entrypoints as entrypoints_mod
from sheeprl_tpu.analysis.ir.__main__ import main as ir_main
from sheeprl_tpu.analysis.ir.rules import (
    check_callbacks,
    check_collectives,
    check_constants,
    check_donation,
    check_dtype_promotion,
    lower_entry,
    measured_budget,
)
from sheeprl_tpu.analysis.ir.types import AuditEntry


def _entry(fn, args, **kw):
    return AuditEntry(name=kw.pop("name", "test/entry"), fn=fn, args=args, **kw)


# ------------------------------------------------------------------------ IR001
def test_ir001_flags_unaliased_donated_buffer():
    def f(big, y):
        return big.sum() + y  # no output can reuse big's (64, 64) buffer

    fn = jax.jit(f, donate_argnums=(0,))
    art = lower_entry(_entry(fn, (jnp.zeros((64, 64)), jnp.zeros(()))))
    findings = check_donation(art)
    assert [f.rule for f in findings] == ["IR001"]
    assert "NOT aliased" in findings[0].message


def test_ir001_clean_when_donation_applies():
    def f(x, y):
        return x * 2 + y

    fn = jax.jit(f, donate_argnums=(0,))
    art = lower_entry(_entry(fn, (jnp.zeros((64, 64)), jnp.zeros((64, 64)))))
    assert check_donation(art) == []


def test_ir001_scalar_slack_tolerated():
    # A refreshed scalar counter (the Anakin episode-sum pattern) stays under the
    # slack; the same shortfall above the slack threshold fires.
    def f(counter, x):
        return jnp.zeros(()), x * 2

    fn = jax.jit(f, donate_argnums=(0,))
    art = lower_entry(_entry(fn, (jnp.zeros(()), jnp.zeros((8,)))))
    assert check_donation(art) == []
    assert check_donation(art, slack_bytes=0) != []


# ------------------------------------------------------------------------ IR002
def test_ir002_flags_f32_dot_under_declared_bf16():
    def f(a, b):
        return a @ b

    a = jnp.zeros((8, 8), jnp.float32)
    art = lower_entry(_entry(jax.jit(f), (a, a), precision="bf16-mixed"))
    findings = check_dtype_promotion(art)
    assert [f.rule for f in findings] == ["IR002"]
    assert "float32" in findings[0].message


def test_ir002_clean_for_bf16_dot_and_declared_fp32():
    def f(a, b):
        return a @ b

    bf = jnp.zeros((8, 8), jnp.bfloat16)
    art = lower_entry(_entry(jax.jit(f), (bf, bf), precision="bf16-mixed"))
    assert check_dtype_promotion(art) == []
    f32 = jnp.zeros((8, 8), jnp.float32)
    art = lower_entry(_entry(jax.jit(f), (f32, f32), precision="fp32"))
    assert check_dtype_promotion(art) == []


# ------------------------------------------------------------------------ IR003
def _scan_with_callback():
    def f(x):
        def body(c, _):
            jax.debug.callback(lambda v: None, c)
            return c + 1, c

        out, _ = jax.lax.scan(body, x, None, length=4)
        return out

    return jax.jit(f)


def test_ir003_flags_callback_inside_scan():
    art = lower_entry(_entry(_scan_with_callback(), (jnp.zeros(()),)))
    findings = check_callbacks(art)
    assert [f.rule for f in findings] == ["IR003"]
    assert "scan/while" in findings[0].message


def test_ir003_gate_and_top_level_callback_are_clean():
    art = lower_entry(_entry(_scan_with_callback(), (jnp.zeros(()),), callbacks_gated=True))
    assert check_callbacks(art) == []

    def g(x):
        jax.debug.callback(lambda v: None, x)  # hot-loop rule only: top level ok
        return x + 1

    art = lower_entry(_entry(jax.jit(g), (jnp.zeros(()),)))
    assert check_callbacks(art) == []


# ------------------------------------------------------------------------ IR004
def test_ir004_flags_collective_in_single_mesh_graph():
    from sheeprl_tpu.parallel.mesh import build_mesh, shard_map_compat
    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(devices=jax.devices()[:1])

    def f(x):
        return shard_map_compat(lambda v: jax.lax.psum(v, "data"), mesh, (P("data"),), P())(x)

    art = lower_entry(_entry(jax.jit(f), (jnp.zeros((8,)),)))
    findings = check_collectives(art)
    assert [f.rule for f in findings] == ["IR004"]
    assert "psum" in findings[0].message
    # a multi-mesh entry declares single_mesh=False and is exempt
    art = lower_entry(_entry(jax.jit(f), (jnp.zeros((8,)),), single_mesh=False))
    assert check_collectives(art) == []


# ------------------------------------------------------------------------ IR005
def test_ir005_flags_oversize_baked_constant():
    baked = jnp.asarray(np.zeros((64, 1024), np.float32))  # 256 KiB closure const

    def f(x):
        return (x * baked).sum()

    art = lower_entry(_entry(jax.jit(f), (jnp.zeros((1024,)),)))
    findings = check_constants(art, max_const_bytes=128 * 1024)
    assert [f.rule for f in findings] == ["IR005"]
    assert check_constants(art, max_const_bytes=1024 * 1024) == []


# ------------------------------------------------------------------------ IR006
def test_ir006_budget_drift_unit():
    measured = {"a": {"total_bytes": 1000}, "new": {"total_bytes": 10}}
    baseline = {
        "meta": {"tolerance": 0.25, "abs_slack_bytes": 0},
        "entries": {"a": {"total_bytes": 500}, "gone": {"total_bytes": 5}},
    }
    findings = budgets_mod.check_budgets(measured, baseline)
    details = sorted(f.detail for f in findings)
    assert details == ["budget-exceeded", "no-budget-row", "stale-budget-row"]
    # within tolerance: no drift finding
    ok = budgets_mod.check_budgets({"a": {"total_bytes": 600}}, baseline)
    assert [f.detail for f in ok] == ["no-budget-row", "stale-budget-row"] or all(
        f.detail != "budget-exceeded" for f in ok
    )
    assert budgets_mod.check_budgets({"a": {"total_bytes": 1}}, None)[0].detail == "missing-baseline"


# ------------------------------------------------------------- CLI (exit codes)
HOOKS_MODULE = """
import jax
import jax.numpy as jnp

from sheeprl_tpu.analysis.ir.types import AuditEntry


def good():
    def f(x, y):
        return x * 2 + y

    fn = jax.jit(f, donate_argnums=(0,))
    z = jnp.zeros((32, 32))
    return [AuditEntry(name="good/entry", fn=fn, args=(z, z), covers=("good",))]


def bad_donation():
    def f(big, y):
        return big.sum() + y  # the donated (64, 64) buffer backs NO output

    fn = jax.jit(f, donate_argnums=(0,))
    return [AuditEntry(name="bad/entry", fn=fn, args=(jnp.zeros((64, 64)), jnp.zeros(())), covers=("bad",))]
"""


@pytest.fixture()
def synthetic_registry(tmp_path, monkeypatch):
    """Point the audit registry at a synthetic hooks module in tmp_path; returns
    a function selecting which hooks the registry exposes."""
    (tmp_path / "ir_synthetic_hooks.py").write_text(textwrap.dedent(HOOKS_MODULE))
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.chdir(tmp_path)

    def select(**hooks):
        registry = {name: f"ir_synthetic_hooks:{fn}" for name, fn in hooks.items()}
        monkeypatch.setattr(entrypoints_mod, "REGISTRY", registry)
        monkeypatch.setattr(entrypoints_mod, "EXPECTED_COVERAGE", frozenset(hooks))
        return registry

    return select


def test_cli_clean_registry_exits_zero(synthetic_registry, capsys):
    synthetic_registry(good="good")
    assert ir_main(["--write-budgets", "-q"]) == 0
    assert ir_main(["-q"]) == 0


def test_cli_ir001_real_violation_nonzero_exit(synthetic_registry, capsys):
    """Acceptance: a REAL un-donated buffer (donate_argnums the compiled HLO does
    not alias) makes the audit exit non-zero."""
    synthetic_registry(bad="bad_donation")
    assert ir_main(["--write-budgets", "-q"]) == 0  # budgets green; IR001 is the finding
    rc = ir_main(["-q"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "IR001" in out


def test_cli_ir006_budget_inflation_nonzero_exit(synthetic_registry, tmp_path, capsys):
    """Acceptance: a compile-memory budget inflation past the tolerance makes the
    audit exit non-zero (baseline shrunk 10x == program grew 10x)."""
    synthetic_registry(good="good")
    assert ir_main(["--write-budgets", "-q"]) == 0
    doc = json.loads((tmp_path / "irbudgets.json").read_text())
    for row in doc["entries"].values():
        for k in row:
            row[k] = max(row[k] // 10, 1)
    (tmp_path / "irbudgets.json").write_text(json.dumps(doc))
    rc = ir_main(["-q"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "IR006" in out and "budget exceeded" in out


def test_cli_coverage_floor_fails_closed(synthetic_registry, capsys):
    synthetic_registry(good="good")
    ir_main(["--write-budgets", "-q"])
    # the floor demands an entry point no hook covers anymore -> IR000
    entrypoints_mod.EXPECTED_COVERAGE = frozenset({"good", "vanished"})
    rc = ir_main(["-q"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "IR000" in out and "vanished" in out


def test_cli_list_and_unknown_entry(synthetic_registry, capsys):
    synthetic_registry(good="good")
    assert ir_main(["--list"]) == 0
    assert "good/entry" in capsys.readouterr().out
    assert ir_main(["--entry", "nope"]) == 2


def test_measured_budget_reports_alias_bytes():
    def f(x, y):
        return x * 2 + y

    fn = jax.jit(f, donate_argnums=(0,))
    z = jnp.zeros((32, 32))
    art = lower_entry(_entry(fn, (z, z)))
    budget = measured_budget(art)
    assert budget["alias_bytes"] == z.size * 4
    assert budget["total_bytes"] >= budget["temp_bytes"]
