"""jaxlint-threads: one positive and one negative fixture per rule (JL008–JL012),
baseline / suppression / CLI exit-code paths, and the runtime lock-order detector."""

import json
import textwrap
import threading

import pytest

from sheeprl_tpu.analysis.engine import load_baseline, run_lint, write_baseline
from sheeprl_tpu.analysis.threads import default_thread_rules
from sheeprl_tpu.analysis.threads import runtime as race_runtime
from sheeprl_tpu.analysis.threads.__main__ import main as threads_main
from tests.test_analysis.conftest import rule_ids


@pytest.fixture()
def tlint(tmp_path):
    """tlint(source, select=[...]) -> concurrency findings for one module."""

    def _lint(source, select=None):
        mod = tmp_path / "snippet.py"
        mod.write_text(textwrap.dedent(source))
        return run_lint([mod], rules=default_thread_rules(select), root=tmp_path)

    return _lint


# ------------------------------------------------------------------------- JL008
def test_jl008_positive_unguarded_cross_method(tlint):
    findings = tlint(
        """
        import threading

        class Racy:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._work, daemon=True)
                self._t.start()

            def _work(self):
                self.count += 1

            def bump(self):
                self.count += 1
        """
    )
    assert "JL008" in rule_ids(findings)


def test_jl008_negative_guarded(tlint):
    findings = tlint(
        """
        import threading

        class Guarded:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._work, daemon=True)
                self._t.start()

            def _work(self):
                with self._lock:
                    self.count += 1

            def bump(self):
                with self._lock:
                    self.count += 1
        """
    )
    assert "JL008" not in rule_ids(findings)


def test_jl008_positive_multi_instance_rmw(tlint):
    # one reader thread per accepted connection: the *same* method races with
    # itself across instances of the thread, so a bare += is a lost update
    findings = tlint(
        """
        import threading

        class Server:
            def __init__(self):
                self.accepted = 0

            def serve(self):
                while True:
                    t = threading.Thread(target=self._reader, daemon=True)
                    t.start()

            def _reader(self):
                self.accepted += 1
        """
    )
    assert "JL008" in rule_ids(findings)


def test_jl008_negative_init_only_write(tlint):
    findings = tlint(
        """
        import threading

        class InitOnly:
            def __init__(self):
                self.mode = "idle"
                self._t = threading.Thread(target=self._work, daemon=True)
                self._t.start()

            def _work(self):
                print(self.mode)
        """
    )
    assert "JL008" not in rule_ids(findings)


# ------------------------------------------------------------------------- JL009
def test_jl009_positive_inverted_with(tlint):
    findings = tlint(
        """
        import threading

        class Inverted:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """
    )
    assert "JL009" in rule_ids(findings)


def test_jl009_positive_multi_item_with_ordering(tlint):
    # `with a, b` acquires left-to-right: reversing the items is an inversion
    findings = tlint(
        """
        import threading

        def multi_item():
            a = threading.Lock()
            b = threading.Lock()
            with a, b:
                pass
            with b, a:
                pass
        """
    )
    assert "JL009" in rule_ids(findings)


def test_jl009_positive_cross_method_edge(tlint):
    findings = tlint(
        """
        import threading

        class CrossMethod:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def left(self):
                with self._a:
                    self.helper()

            def helper(self):
                with self._b:
                    pass

            def right(self):
                with self._b:
                    with self._a:
                        pass
        """
    )
    assert "JL009" in rule_ids(findings)


def test_jl009_negative_rlock_reentrancy(tlint):
    # re-entering the same RLock through a self-call is not a cycle
    findings = tlint(
        """
        import threading

        class Reentrant:
            def __init__(self):
                self._r = threading.RLock()

            def outer(self):
                with self._r:
                    self.inner()

            def inner(self):
                with self._r:
                    pass
        """
    )
    assert "JL009" not in rule_ids(findings)


def test_jl009_negative_consistent_order(tlint):
    findings = tlint(
        """
        import threading

        class Ordered:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """
    )
    assert "JL009" not in rule_ids(findings)


def test_jl009_positive_plain_lock_self_deadlock(tlint):
    findings = tlint(
        """
        import threading

        class SelfDeadlock:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """
    )
    assert "JL009" in rule_ids(findings)


# ------------------------------------------------------------------------- JL010
def test_jl010_positive_sleep_and_blocking_get(tlint):
    findings = tlint(
        """
        import queue
        import threading
        import time

        class SleepUnderLock:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)
                    self._q.get()
        """,
        select=["JL010"],
    )
    assert len(findings) == 2


def test_jl010_negative_nonblocking_queue_ops(tlint):
    findings = tlint(
        """
        import queue
        import threading

        class NonBlocking:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def ok(self):
                with self._lock:
                    self._q.get(block=False)
                    self._q.get_nowait()
                    self._q.put_nowait(1)
        """,
        select=["JL010"],
    )
    assert findings == []


def test_jl010_negative_condition_own_lock(tlint):
    # Condition.wait releases its own backing lock: not blocking-under-lock
    findings = tlint(
        """
        import threading

        class CondOwn:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self.ready = False

            def wait_ready(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait()
        """,
        select=["JL010"],
    )
    assert findings == []


def test_jl010_positive_condition_wait_with_other_lock(tlint):
    findings = tlint(
        """
        import threading

        class CondOther:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self.ready = False

            def wait_ready(self):
                with self._other:
                    with self._cond:
                        while not self.ready:
                            self._cond.wait()
        """,
        select=["JL010"],
    )
    assert len(findings) == 1


# ------------------------------------------------------------------------- JL011
def test_jl011_positive_never_joined_nondaemon(tlint):
    findings = tlint(
        """
        import threading

        class NoJoin:
            def spawn(self):
                t = threading.Thread(target=self.spin)
                t.start()

            def spin(self):
                for _ in range(3):
                    pass
        """,
        select=["JL011"],
    )
    assert "JL011" in rule_ids(findings)


def test_jl011_positive_unstoppable_loop(tlint):
    findings = tlint(
        """
        import threading

        class Unstoppable:
            def __init__(self):
                self._t = threading.Thread(target=self._spin, daemon=True)
                self._t.start()

            def _spin(self):
                while True:
                    pass
        """,
        select=["JL011"],
    )
    assert "JL011" in rule_ids(findings)


def test_jl011_positive_start_before_dependent_attr(tlint):
    findings = tlint(
        """
        import threading

        class EarlyStart:
            def __init__(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
                self.needed = 5

            def _run(self):
                print(self.needed)
        """,
        select=["JL011"],
    )
    assert "JL011" in rule_ids(findings)


def test_jl011_negative_joined_daemon_with_stop(tlint):
    findings = tlint(
        """
        import threading

        class Clean:
            def __init__(self):
                self._stop = threading.Event()
                self.needed = 5
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                while not self._stop.is_set():
                    print(self.needed)

            def close(self):
                self._stop.set()
                self._t.join()
        """,
        select=["JL011"],
    )
    assert findings == []


# ------------------------------------------------------------------------- JL012
def test_jl012_positive_wait_without_loop(tlint):
    findings = tlint(
        """
        import threading

        class BadWait:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self.ready = False

            def wait_ready(self):
                with self._cond:
                    if not self.ready:
                        self._cond.wait()
        """,
        select=["JL012"],
    )
    assert "JL012" in rule_ids(findings)


def test_jl012_negative_predicate_loop_and_event(tlint):
    findings = tlint(
        """
        import threading

        class GoodWait:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._ev = threading.Event()
                self.ready = False

            def wait_ready(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait()

            def wait_for(self):
                with self._cond:
                    self._cond.wait_for(lambda: self.ready)

            def wait_event(self):
                self._ev.wait()
        """,
        select=["JL012"],
    )
    assert findings == []


# --------------------------------------------------- suppression / baseline / CLI
_INVERTED_SRC = """
import threading

class Inverted:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""


def test_suppression_comment(tmp_path):
    src = textwrap.dedent(
        """
        import threading
        import time

        class Suppressed:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    # jaxlint: disable=JL010 -- intentional: test fixture
                    time.sleep(1.0)
        """
    )
    mod = tmp_path / "snippet.py"
    mod.write_text(src)
    findings = run_lint([mod], rules=default_thread_rules(["JL010"]), root=tmp_path)
    assert findings == []


def test_baseline_round_trip(tmp_path):
    mod = tmp_path / "snippet.py"
    mod.write_text(textwrap.dedent(_INVERTED_SRC))
    rules = default_thread_rules(["JL009"])
    findings = run_lint([mod], rules=rules, root=tmp_path)
    assert findings

    base_path = tmp_path / "threads.baseline"
    write_baseline(findings, str(base_path))
    baseline = load_baseline(str(base_path))
    again = run_lint([mod], rules=rules, baseline=baseline, root=tmp_path)
    assert again == []


def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text(textwrap.dedent(_INVERTED_SRC))
    base = tmp_path / "threads.baseline"

    assert threads_main(["--no-baseline", "-q", str(clean)]) == 0
    assert threads_main(["--no-baseline", "-q", str(dirty)]) == 1
    assert threads_main(["--select", "JL999", str(clean)]) == 2

    # --write-baseline accepts the current findings; the next run is green
    assert threads_main(["--write-baseline", "--baseline", str(base), "-q", str(dirty)]) == 0
    assert threads_main(["--baseline", str(base), "-q", str(dirty)]) == 0
    capsys.readouterr()


def test_repo_is_clean_against_committed_baseline():
    # the acceptance bar: jaxlint-threads over the package exits 0
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[2]
    assert threads_main(
        ["--baseline", str(root / "threads.baseline"), "--root", str(root), "-q", str(root / "sheeprl_tpu")]
    ) == 0


# ------------------------------------------------------------- runtime detector
def test_runtime_detects_two_thread_lock_order_inversion(tmp_path):
    det = race_runtime.RaceDetector(log_dir=str(tmp_path))
    a = det.make_lock()
    b = det.make_lock()

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join()

    cycles = det.cycles()
    assert len(cycles) == 1
    assert set(cycles[0]) == {a.name, b.name}
    counts = det.counts()
    assert counts["cycles"] == 1
    assert counts["edges"] == 2

    path = det.dump("test")
    lines = [json.loads(line) for line in open(path)]
    kinds = [rec["kind"] for rec in lines]
    assert kinds[0] == "summary" and lines[0]["cycles"] == 1
    assert "cycle" in kinds and "edge" in kinds


def test_runtime_rlock_reentry_is_not_a_cycle():
    det = race_runtime.RaceDetector()
    r = det.make_rlock()
    with r:
        with r:
            pass
    assert det.cycles() == []
    assert det.counts()["edges"] == 0


def test_runtime_consistent_order_no_cycle():
    det = race_runtime.RaceDetector()
    a, b = det.make_lock(), det.make_lock()
    for _ in range(3):
        with a:
            with b:
                pass
    assert det.cycles() == []
    assert det.counts()["edges"] == 1


def test_runtime_long_hold_recorded():
    det = race_runtime.RaceDetector(held_threshold_ms=1.0)
    lock = det.make_lock()
    with lock:
        threading.Event().wait(0.01)
    rep = det.report()
    assert len(rep["long_holds"]) == 1
    assert rep["long_holds"][0]["lock"] == lock.name


def test_runtime_note_blocking_only_under_lock():
    det = race_runtime.RaceDetector()
    det.note_blocking("time.sleep(1)")  # nothing held: ignored
    lock = det.make_lock()
    with lock:
        det.note_blocking("time.sleep(1)")
    blocking = det.report()["blocking"]
    assert len(blocking) == 1
    assert blocking[0]["held"] == [lock.name]


def test_runtime_condition_wait_interop():
    # a real threading.Condition over an instrumented lock: wait/notify works
    # and the held-set is exact afterwards (the Condition private protocol)
    det = race_runtime.RaceDetector()
    lock = det.make_lock()
    cond = race_runtime._REAL_CONDITION(lock)
    ready = []

    def waiter():
        with cond:
            while not ready:
                cond.wait(timeout=5)

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        ready.append(1)
        cond.notify()
    t.join(timeout=5)
    assert not t.is_alive()
    assert det.held_names() == []


def test_runtime_install_uninstall_round_trip():
    det = race_runtime.RaceDetector()
    prev = race_runtime.install(det)
    try:
        assert race_runtime.get_active() is det
        lock = threading.Lock()
        assert isinstance(lock, race_runtime._InstrumentedLock)
        with lock:
            pass
        assert det.counts()["acquisitions"] >= 1
    finally:
        # compose with a session-installed detector (CI race runs): restore it
        if prev is not None:
            race_runtime.install(prev)
        else:
            race_runtime.uninstall()
    if prev is None:
        assert threading.Lock is race_runtime._REAL_LOCK
        assert race_runtime.get_active() is None


def test_runtime_env_gate(monkeypatch):
    monkeypatch.delenv(race_runtime.ENV_VAR, raising=False)
    assert not race_runtime.enabled_by_env()
    assert race_runtime.maybe_install() is None
    monkeypatch.setenv(race_runtime.ENV_VAR, "0")
    assert not race_runtime.enabled_by_env()
    monkeypatch.setenv(race_runtime.ENV_VAR, "1")
    assert race_runtime.enabled_by_env()


def test_runtime_maybe_install_from_config(tmp_path, monkeypatch):
    monkeypatch.delenv(race_runtime.ENV_VAR, raising=False)
    prev = race_runtime.get_active()
    cfg = {"analysis": {"race_detect": True, "race_hold_ms": 50.0}}
    det = race_runtime.maybe_install(cfg, log_dir=str(tmp_path))
    try:
        assert det is not None
        assert det.held_threshold_s == pytest.approx(0.05)
    finally:
        if prev is not None:
            race_runtime.install(prev)
        else:
            race_runtime.uninstall()
