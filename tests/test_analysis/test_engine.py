"""Engine mechanics: suppression comments, baseline round trip, CLI exit codes."""

import textwrap

from sheeprl_tpu.analysis.engine import (
    Finding,
    filter_baseline,
    load_baseline,
    parse_suppressions,
    run_lint,
    write_baseline,
)
from sheeprl_tpu.analysis.rules import default_rules

_REUSE = """
import jax

def f(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,)){suffix}
    return a + b
"""


def _lint_file(tmp_path, source, **kwargs):
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent(source))
    return run_lint([mod], rules=default_rules(["JL001"]), root=tmp_path, **kwargs)


def test_same_line_suppression(tmp_path):
    assert _lint_file(tmp_path, _REUSE.format(suffix="")) != []
    assert _lint_file(tmp_path, _REUSE.format(suffix="  # jaxlint: disable=JL001")) == []


def test_suppression_tolerates_trailing_prose(tmp_path):
    src = _REUSE.format(suffix="  # jaxlint: disable=JL001 (correlated draws are intentional here)")
    assert _lint_file(tmp_path, src) == []


def test_standalone_comment_suppresses_next_line(tmp_path):
    src = """
    import jax

    def f(key):
        a = jax.random.normal(key, (3,))
        # jaxlint: disable=JL001
        b = jax.random.uniform(key, (3,))
        return a + b
    """
    assert _lint_file(tmp_path, src) == []


def test_disable_all_and_other_rule(tmp_path):
    assert _lint_file(tmp_path, _REUSE.format(suffix="  # jaxlint: disable=all")) == []
    # suppressing a different rule leaves the finding alone
    assert _lint_file(tmp_path, _REUSE.format(suffix="  # jaxlint: disable=JL005")) != []


def test_parse_suppressions_map():
    src = "x = 1  # jaxlint: disable=JL001,JL004\n# jaxlint: disable=all\ny = 2\n"
    sup = parse_suppressions(src)
    assert sup[1] == {"JL001", "JL004"}
    assert sup[3] == {"all"}


def test_baseline_round_trip(tmp_path):
    findings = _lint_file(tmp_path, _REUSE.format(suffix=""))
    assert findings
    baseline_path = tmp_path / "base.txt"
    write_baseline(findings, baseline_path)
    baseline = load_baseline(baseline_path)
    assert filter_baseline(findings, baseline) == []
    # a different finding is NOT filtered
    other = Finding("JL001", "elsewhere.py", 1, 0, "msg", "f:key")
    assert filter_baseline([other], baseline) == [other]
    # and the baseline also filters through run_lint itself
    assert _lint_file(tmp_path, _REUSE.format(suffix=""), baseline=baseline) == []


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(tmp_path / "nope.txt") == set()


def test_cli_exit_codes(tmp_path):
    from sheeprl_tpu.analysis.__main__ import main

    mod = tmp_path / "m.py"
    mod.write_text(textwrap.dedent(_REUSE.format(suffix="")))
    base = tmp_path / "b.txt"
    assert main([str(mod), "--no-baseline", "--root", str(tmp_path), "-q"]) == 1
    assert main([str(mod), "--write-baseline", "--baseline", str(base), "--root", str(tmp_path), "-q"]) == 0
    assert main([str(mod), "--baseline", str(base), "--root", str(tmp_path), "-q"]) == 0
    assert main([str(mod), "--select", "JL999"]) == 2
