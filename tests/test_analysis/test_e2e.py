"""End-to-end: the linter runs over the real package and is green vs the baseline."""

from pathlib import Path

from sheeprl_tpu.analysis.engine import load_baseline, run_lint

REPO_ROOT = Path(__file__).resolve().parents[2]
PACKAGE = REPO_ROOT / "sheeprl_tpu"
BASELINE = REPO_ROOT / "jaxlint.baseline"


def test_linter_runs_over_package_without_crashing():
    findings = run_lint([PACKAGE], config_dir=PACKAGE / "config" / "configs", root=REPO_ROOT)
    # structural sanity on whatever it reports
    for f in findings:
        assert f.rule.startswith("JL") and f.line >= 1 and f.path


def test_package_is_green_against_committed_baseline():
    findings = run_lint(
        [PACKAGE],
        config_dir=PACKAGE / "config" / "configs",
        baseline=load_baseline(BASELINE),
        root=REPO_ROOT,
    )
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_module_green_against_baseline():
    from sheeprl_tpu.analysis.__main__ import main

    rc = main([str(PACKAGE), "--baseline", str(BASELINE), "--root", str(REPO_ROOT), "-q"])
    assert rc == 0
