"""End-to-end: the linter runs over the real package and is green vs the baseline."""

from pathlib import Path

import pytest

from sheeprl_tpu.analysis.engine import load_baseline, run_lint

REPO_ROOT = Path(__file__).resolve().parents[2]
PACKAGE = REPO_ROOT / "sheeprl_tpu"
BASELINE = REPO_ROOT / "jaxlint.baseline"


def test_linter_runs_over_package_without_crashing():
    findings = run_lint([PACKAGE], config_dir=PACKAGE / "config" / "configs", root=REPO_ROOT)
    # structural sanity on whatever it reports
    for f in findings:
        assert f.rule.startswith("JL") and f.line >= 1 and f.path


def test_package_is_green_against_committed_baseline():
    findings = run_lint(
        [PACKAGE],
        config_dir=PACKAGE / "config" / "configs",
        baseline=load_baseline(BASELINE),
        root=REPO_ROOT,
    )
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_module_green_against_baseline():
    from sheeprl_tpu.analysis.__main__ import main

    rc = main([str(PACKAGE), "--baseline", str(BASELINE), "--root", str(REPO_ROOT), "-q"])
    assert rc == 0


# ------------------------------------------------------------------- IR audit
def test_ir_audit_one_real_entry_green_against_committed_budgets(monkeypatch):
    """Tier-1 slice of the CI ir-audit job: ONE cheap real entry point lowers,
    compiles, passes IR001-IR005 and matches the checked-in irbudgets.json."""
    import os

    from sheeprl_tpu.analysis.ir.__main__ import main as ir_main

    monkeypatch.chdir(REPO_ROOT)
    assert os.path.isfile("irbudgets.json"), "irbudgets.json must be committed"
    assert ir_main(["--entry", "ppo", "-q"]) == 0


@pytest.mark.slow
def test_ir_audit_full_registry_covers_all_entry_points(monkeypatch):
    """The whole registry audits green over HEAD and covers the 14 entry points
    + both Anakin dispatches (the CI ir-audit job's in-repo twin)."""
    from sheeprl_tpu.analysis.ir import EXPECTED_COVERAGE, build_entries
    from sheeprl_tpu.analysis.ir.__main__ import main as ir_main

    covered = set()
    for entry in build_entries():
        covered.update(entry.covers)
    assert EXPECTED_COVERAGE <= covered, sorted(EXPECTED_COVERAGE - covered)
    # the 14 entry points + 4 anakin dispatches (plain + population for ppo/sac)
    # + 2 serve act programs + 4 precision-tier programs (bf16 anakin, int8
    # serve); p2e finetuning rides the dreamer-family builders on top
    assert len(EXPECTED_COVERAGE) == 24
    monkeypatch.chdir(REPO_ROOT)
    assert ir_main(["-q"]) == 0
