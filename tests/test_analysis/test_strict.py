"""Runtime strict mode: signature guards, NaN scans, and the watchdog hard error."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.analysis.strict import (
    NonFiniteError,
    SignatureDriftError,
    assert_finite,
    clear_pending,
    nan_scan,
    raise_pending,
    registered_guards,
    strict_enabled,
    strict_guard,
)
from sheeprl_tpu.obs.monitor import TrainingMonitor
from sheeprl_tpu.obs.watchdog import RecompileError

STRICT = {"analysis": {"strict": True}}
LAX = {"analysis": {"strict": False}}


@pytest.fixture(autouse=True)
def _clean_pending():
    clear_pending()
    yield
    clear_pending()


def test_strict_enabled_parsing():
    assert strict_enabled(STRICT)
    assert not strict_enabled(LAX)
    assert not strict_enabled({})
    assert not strict_enabled(None)
    assert not strict_enabled({"analysis": None})


# ------------------------------------------------------------- signature guard
def test_guard_passes_stable_signature_and_registers():
    f = strict_guard(STRICT, "test/stable", jax.jit(lambda x: x + 1))
    x = np.ones((4, 2), np.float32)
    assert np.allclose(f(x), x + 1)
    assert np.allclose(f(x), x + 1)
    assert "test/stable" in registered_guards()


def test_guard_raises_on_shape_drift():
    f = strict_guard(STRICT, "test/drift", jax.jit(lambda x: x * 2))
    f(np.ones(3, np.float32))
    with pytest.raises(SignatureDriftError, match="drifting signature"):
        f(np.ones(5, np.float32))


def test_guard_raises_on_dtype_drift():
    f = strict_guard(STRICT, "test/dtype", jax.jit(lambda x: x * 2))
    f(np.ones(3, np.float32))
    with pytest.raises(SignatureDriftError):
        f(np.ones(3, np.float64))


def test_guard_raises_on_structure_drift():
    f = strict_guard(STRICT, "test/tree", jax.jit(lambda t: jax.tree.map(lambda v: v * 2, t)))
    f({"a": np.ones(3, np.float32)})
    with pytest.raises(SignatureDriftError):
        f({"a": np.ones(3, np.float32), "b": np.ones(3, np.float32)})


def test_guard_is_identity_when_off():
    fn = jax.jit(lambda x: x)
    assert strict_guard(LAX, "test/off", fn) is fn


# ----------------------------------------------------------------- NaN scanning
def test_nan_scan_inside_jit_detected_at_boundary():
    @jax.jit
    def step(x):
        y = x / x  # NaN at 0
        nan_scan({"loss": y}, "test/step")
        return y

    jax.block_until_ready(step(jnp.zeros(3)))
    with pytest.raises(NonFiniteError, match="loss"):
        raise_pending()
    raise_pending()  # drained: second call is clean


def test_nan_scan_clean_values_do_not_raise():
    @jax.jit
    def step(x):
        nan_scan({"loss": x * 2}, "test/clean")
        return x

    jax.block_until_ready(step(jnp.ones(3)))
    raise_pending()


def test_assert_finite_host_side():
    with pytest.raises(NonFiniteError, match="bad"):
        assert_finite(STRICT, {"bad": np.array([1.0, np.nan])}, "test")
    assert_finite(STRICT, {"ok": np.ones(3), "ints": np.arange(3)}, "test")
    # off: no-op even on NaN
    assert_finite(LAX, {"bad": np.array([np.inf])}, "test")


# --------------------------------------------------- watchdog: warning -> error
def _monitor(strict: bool, tmp_path):
    cfg = {
        "obs": {"enabled": True, "trace": False, "telemetry": False, "xprof_annotations": False},
        "analysis": {"strict": strict},
    }
    return TrainingMonitor(cfg, str(tmp_path))


def test_forced_post_warmup_recompile_is_hard_error_in_strict(tmp_path):
    m = _monitor(True, tmp_path)
    try:
        @jax.jit
        def f(x):
            return jnp.sin(x)

        x3 = jax.device_put(np.ones(3, np.float32))
        x7 = jax.device_put(np.ones(7, np.float32))
        jax.block_until_ready(f(x3))
        m.advance()  # update 1: warmup
        m.advance()  # update 2: mark_warm
        jax.block_until_ready(f(x7))  # forced post-warmup recompile
        with pytest.raises(RecompileError, match="recompilation"):
            m.advance()
    finally:
        m.close()


def test_same_recompile_only_warns_without_strict(tmp_path):
    m = _monitor(False, tmp_path)
    try:
        @jax.jit
        def g(x):
            return jnp.cos(x)

        jax.block_until_ready(g(jax.device_put(np.ones(3, np.float32))))
        m.advance()
        m.advance()
        jax.block_until_ready(g(jax.device_put(np.ones(9, np.float32))))
        with pytest.warns(UserWarning, match="recompilation"):
            m.advance()
    finally:
        m.close()
