"""Helpers for the jaxlint tests: write a snippet to disk, lint it, return findings."""

from __future__ import annotations

import textwrap
from pathlib import Path
from typing import List, Optional

import pytest

from sheeprl_tpu.analysis.engine import Finding, run_lint
from sheeprl_tpu.analysis.rules import default_rules


@pytest.fixture()
def lint(tmp_path):
    """lint(source, select=[...]) -> findings for a single in-memory module."""

    def _lint(source: str, select: Optional[List[str]] = None, config_dir=None) -> List[Finding]:
        mod = tmp_path / "snippet.py"
        mod.write_text(textwrap.dedent(source))
        rules = default_rules(select) if select else default_rules(
            ["JL001", "JL002", "JL003", "JL004", "JL005"]  # JL006 needs a config tree
        )
        return run_lint([mod], rules=rules, config_dir=config_dir, root=tmp_path)

    return _lint


def rule_ids(findings: List[Finding]) -> List[str]:
    return [f.rule for f in findings]
