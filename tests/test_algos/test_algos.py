"""End-to-end dry-run smoke tests through the real CLI, per algorithm × dummy env
(the reference's dominant test pattern, ``tests/test_algos/test_algos.py:21-566``)."""

import os
import pytest

from sheeprl_tpu.cli import run


def standard_args(tmp_path, extra=()):
    return [
        "dry_run=True",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "checkpoint.every=1",
        "checkpoint.save_last=True",
        "metric.log_every=1",
        f"log_root={tmp_path}",
        "buffer.memmap=False",
        *extra,
    ]


def _ckpts(tmp_path):
    # mtime order: lexicographic sort would put ckpt_8 after ckpt_32
    return sorted(tmp_path.rglob("ckpt_*"), key=lambda p: p.stat().st_mtime)


PPO_ARGS = [
    "exp=ppo",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=8",
    "algo.update_epochs=1",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.encoder.cnn_features_dim=16",
    "algo.encoder.mlp_features_dim=8",
]


@pytest.mark.parametrize("env_id", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"])
def test_ppo_dummy_envs(tmp_path, env_id):
    run(
        PPO_ARGS
        + [f"env={env_id}", "algo.mlp_keys.encoder=[state]", "algo.cnn_keys.encoder=[rgb]"]
        + standard_args(tmp_path)
    )


def test_ppo_vector_obs_only(tmp_path):
    run(PPO_ARGS + ["env=discrete_dummy", "algo.mlp_keys.encoder=[state]"] + standard_args(tmp_path))


def test_ppo_resume_from_checkpoint(tmp_path):
    run(
        PPO_ARGS
        + ["env=discrete_dummy", "algo.mlp_keys.encoder=[state]", "algo.total_steps=32"]
        + standard_args(tmp_path)
    )
    ckpts = _ckpts(tmp_path)
    assert ckpts, "no checkpoint written"
    run(
        PPO_ARGS
        + [
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            f"checkpoint.resume_from={ckpts[-1]}",
        ]
        + standard_args(tmp_path)
    )


def test_ppo_evaluate_roundtrip(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(PPO_ARGS + ["env=discrete_dummy", "algo.mlp_keys.encoder=[state]"] + standard_args(tmp_path))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


SAC_ARGS = [
    "exp=sac",
    "env=continuous_dummy",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=8",
    "algo.per_rank_batch_size=8",
    "algo.learning_starts=4",
    "algo.total_steps=16",
    "buffer.size=256",
]


def test_sac_dummy_env(tmp_path):
    run(SAC_ARGS + standard_args(tmp_path, extra=["dry_run=False"]))


@pytest.mark.slow
def test_sac_resume_and_evaluate(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(SAC_ARGS + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    run(SAC_ARGS + [f"checkpoint.resume_from={ckpts[-1]}", "algo.total_steps=24"] + standard_args(tmp_path, extra=["dry_run=False"]))
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


DV3_ARGS = [
    "exp=dreamer_v3_dummy",
    "algo.total_steps=32",
    "algo.learning_starts=16",
]


@pytest.mark.parametrize(
    "env_id",
    [
        "discrete_dummy",
        pytest.param("multidiscrete_dummy", marks=pytest.mark.slow),
        pytest.param("continuous_dummy", marks=pytest.mark.slow),
    ],
)
def test_dreamer_v3_dummy_envs(tmp_path, env_id):
    run(DV3_ARGS + [f"env={env_id}"] + standard_args(tmp_path, extra=["dry_run=False"]))


@pytest.mark.slow
def test_dreamer_v3_resume_and_evaluate(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(DV3_ARGS + ["env=discrete_dummy"] + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    run(
        DV3_ARGS
        + ["env=discrete_dummy", f"checkpoint.resume_from={ckpts[-1]}", "algo.total_steps=48"]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


def test_a2c_dummy_env(tmp_path):
    run(
        [
            "exp=a2c",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
        ]
        + standard_args(tmp_path)
    )


def test_droq_dummy_env(tmp_path):
    run(
        [
            "exp=droq",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=4",
            "algo.total_steps=16",
            "buffer.size=256",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )


@pytest.mark.parametrize("env_id", ["discrete_dummy", "continuous_dummy"])
def test_ppo_recurrent_dummy_env(tmp_path, env_id):
    run(
        [
            "exp=ppo_recurrent",
            f"env={env_id}",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=8",
            "algo.per_rank_num_batches=2",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.rnn.lstm.hidden_size=8",
            "algo.mlp_layers=1",
        ]
        + standard_args(tmp_path)
    )


def test_dreamer_v3_device_buffer(tmp_path):
    """buffer.device=True: HBM-resident replay with index-only sampling and the
    in-jit gather train block (single-chip mesh)."""
    run(
        [
            "exp=dreamer_v3_dummy",
            "env=discrete_dummy",
            "buffer.device=True",
            "mesh.devices=1",
            "algo.total_steps=32",
            "algo.learning_starts=16",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    assert _ckpts(tmp_path), "no checkpoint written"


@pytest.mark.slow
@pytest.mark.parametrize("algo", ["dreamer_v1", "dreamer_v2"])
def test_dreamer_v12_device_buffer(tmp_path, algo):
    """buffer.device=True on the DV1/DV2 loops (same HBM-resident replay path as
    DV3; DV2 gated to the sequential buffer type)."""
    run(
        [
            f"exp={algo}_dummy",
            "env=discrete_dummy",
            "buffer.device=True",
            "mesh.devices=1",
            "algo.total_steps=32",
            "algo.learning_starts=16",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    assert _ckpts(tmp_path), "no checkpoint written"


@pytest.mark.slow
def test_dreamer_v3_device_buffer_data_parallel(tmp_path, caplog):
    """buffer.device=True composed with mesh.data=2: the replay ring is env-sharded
    over the data axis (per-shard sampling + shard_map gather) instead of falling
    back to host sampling — the r4 DP-composable fast path."""
    import logging

    with caplog.at_level(logging.WARNING, logger="sheeprl_tpu.data.device_buffer"):
        run(
            [
                "exp=dreamer_v3_dummy",
                "env=discrete_dummy",
                "buffer.device=True",
                "mesh.devices=2",
                "algo.total_steps=32",
                "algo.learning_starts=16",
            ]
            + standard_args(tmp_path, extra=["dry_run=False"])
        )
    fallbacks = [
        r
        for r in caplog.records
        if r.name == "sheeprl_tpu.data.device_buffer" and "falling back" in r.getMessage()
    ]
    assert not fallbacks, "device replay fell back to host sampling under data parallelism"
    assert _ckpts(tmp_path), "no checkpoint written"


def test_ppo_recurrent_attention_sequence_model(tmp_path):
    """The attention sequence-model variant trains end-to-end (dense path)."""
    run(
        [
            "exp=ppo_recurrent",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.sequence_model=attention",
            "algo.attention.num_heads=2",
            "algo.attention.window=8",
            "algo.rollout_steps=8",
            "algo.per_rank_num_batches=2",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.rnn.lstm.hidden_size=8",
            "algo.mlp_layers=1",
        ]
        + standard_args(tmp_path)
    )


def test_ppo_recurrent_attention_sequence_parallel(tmp_path):
    """Ring attention as a USED training path: the attention variant trains with the
    rollout sharded over a 4-way `sequence` mesh axis (VERDICT r2 item 8)."""
    run(
        [
            "exp=ppo_recurrent",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.sequence_model=attention",
            "algo.attention.num_heads=2",
            "algo.attention.window=8",
            "algo.rollout_steps=8",
            "algo.per_rank_num_batches=2",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.rnn.lstm.hidden_size=8",
            "algo.mlp_layers=1",
            "mesh.data=2",
            "mesh.sequence=4",
        ]
        + standard_args(tmp_path)
    )


def test_sac_ae_dummy_env(tmp_path):
    run(
        [
            "exp=sac_ae",
            "env=continuous_dummy",
            "env.screen_size=32",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.encoder.features_dim=8",
            "algo.encoder.channels=4",
            "algo.actor.dense_units=8",
            "algo.critic.dense_units=8",
            "algo.per_rank_batch_size=4",
            "algo.learning_starts=4",
            "algo.total_steps=16",
            "buffer.size=256",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )


DV2_ARGS = [
    "exp=dreamer_v2_dummy",
    "algo.total_steps=32",
    "algo.learning_starts=16",
]


@pytest.mark.parametrize(
    "env_id",
    [
        "discrete_dummy",
        pytest.param("multidiscrete_dummy", marks=pytest.mark.slow),
        pytest.param("continuous_dummy", marks=pytest.mark.slow),
    ],
)
def test_dreamer_v2_dummy_envs(tmp_path, env_id):
    run(DV2_ARGS + [f"env={env_id}"] + standard_args(tmp_path, extra=["dry_run=False"]))


@pytest.mark.slow
def test_dreamer_v2_episode_buffer(tmp_path):
    run(
        DV2_ARGS
        # dummy episodes are 6 steps long; the EpisodeBuffer refuses episodes shorter
        # than the sample sequence length (reference buffers.py:986)
        + ["env=discrete_dummy", "buffer.type=episode", "buffer.prioritize_ends=True", "algo.per_rank_sequence_length=5"]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )


@pytest.mark.slow
def test_dreamer_v2_resume_and_evaluate(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(DV2_ARGS + ["env=discrete_dummy"] + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    run(
        DV2_ARGS
        + ["env=discrete_dummy", f"checkpoint.resume_from={ckpts[-1]}", "algo.total_steps=48"]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


DV1_ARGS = [
    "exp=dreamer_v1_dummy",
    "algo.total_steps=32",
    "algo.learning_starts=16",
]


@pytest.mark.parametrize(
    "env_id",
    [
        "discrete_dummy",
        pytest.param("multidiscrete_dummy", marks=pytest.mark.slow),
        pytest.param("continuous_dummy", marks=pytest.mark.slow),
    ],
)
def test_dreamer_v1_dummy_envs(tmp_path, env_id):
    run(DV1_ARGS + [f"env={env_id}"] + standard_args(tmp_path, extra=["dry_run=False"]))


@pytest.mark.slow
def test_dreamer_v1_resume_and_evaluate(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(DV1_ARGS + ["env=discrete_dummy"] + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    run(
        DV1_ARGS
        + ["env=discrete_dummy", f"checkpoint.resume_from={ckpts[-1]}", "algo.total_steps=48"]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


P2E_DV3_ARGS = [
    "exp=p2e_dv3_dummy",
    "algo.total_steps=32",
    "algo.learning_starts=16",
]


@pytest.mark.parametrize(
    "env_id", ["discrete_dummy", pytest.param("continuous_dummy", marks=pytest.mark.slow)]
)
def test_p2e_dv3_exploration_dummy_envs(tmp_path, env_id):
    run(P2E_DV3_ARGS + [f"env={env_id}"] + standard_args(tmp_path, extra=["dry_run=False"]))


@pytest.mark.slow
def test_p2e_dv3_finetuning_from_exploration(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(P2E_DV3_ARGS + ["env=discrete_dummy"] + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    run(
        P2E_DV3_ARGS
        + [
            "env=discrete_dummy",
            "algo.name=p2e_dv3_finetuning",
            f"checkpoint.exploration_ckpt_path={ckpts[-1]}",
            "buffer.load_from_exploration=True",
            "algo.total_steps=48",
            # deliberately wrong: the exploration run's architecture must win
            # (reference p2e_dv3_finetuning.py:46-69), or template loading crashes
            "algo.dense_units=32",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    fntn_ckpts = _ckpts(tmp_path)
    assert len(fntn_ckpts) > len(ckpts)
    # The player must have switched to the TASK actor at the first training
    # iteration (reference p2e finetuning :350-352) — regression guard.
    from sheeprl_tpu.checkpoint.manager import CheckpointManager

    assert CheckpointManager.load(fntn_ckpts[-1], templates={})["actor_type"] == "task"
    evaluate([f"checkpoint_path={fntn_ckpts[-1]}", "env.capture_video=False"])


@pytest.mark.slow
def test_p2e_dv3_device_buffer_exploration_and_finetuning(tmp_path):
    """buffer.device=True on the P2E-DV3 loops: the exploration loop trains off the
    HBM mirror, and the finetuning loop REBUILDS the mirror from the exploration
    buffer hand-off (mirror.load_from) before its first gradient step."""
    dev = ["buffer.device=True", "mesh.devices=1"]
    run(P2E_DV3_ARGS + ["env=discrete_dummy"] + dev + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    run(
        P2E_DV3_ARGS
        + [
            "env=discrete_dummy",
            "algo.name=p2e_dv3_finetuning",
            f"checkpoint.exploration_ckpt_path={ckpts[-1]}",
            "buffer.load_from_exploration=True",
            "algo.total_steps=48",
        ]
        + dev
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    assert len(_ckpts(tmp_path)) > len(ckpts)


def test_sac_device_buffer_resume(tmp_path):
    """buffer.device=True on SAC: HBM transition ring + fused scanned blocks with
    in-jit index sampling and a donated carry; resume rebuilds the ring (and its
    staleness stamps) from the checkpointed host buffer."""
    dev = ["buffer.device=True", "mesh.devices=1"]
    run(SAC_ARGS + dev + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts, "no checkpoint written"
    run(
        SAC_ARGS
        + dev
        + [f"checkpoint.resume_from={ckpts[-1]}", "algo.total_steps=24"]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )


def test_droq_device_buffer(tmp_path):
    """buffer.device=True on DroQ: the UTD block (K critic updates + actor update)
    runs as ONE fused donated dispatch over the HBM transition ring."""
    run(
        [
            "exp=droq",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=4",
            "algo.total_steps=16",
            "buffer.size=256",
            "buffer.device=True",
            "mesh.devices=1",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    assert _ckpts(tmp_path), "no checkpoint written"


@pytest.mark.slow
def test_sac_decoupled_device_buffer(tmp_path):
    """buffer.device=True on decoupled SAC: the player scatters into the ring
    while the learner runs fused donated blocks; the player acts on copied params
    so donation never invalidates its actor."""
    run(
        [
            "exp=sac_decoupled",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=4",
            "algo.total_steps=16",
            "buffer.size=256",
            "buffer.device=True",
            "mesh.devices=1",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    assert _ckpts(tmp_path), "no checkpoint written"


def test_sac_ae_device_buffer(tmp_path):
    """buffer.device=True on SAC-AE: HBM transition mirror (obs+next_obs rows),
    index-only sampling, in-jit row gather."""
    run(
        [
            "exp=sac_ae",
            "env=continuous_dummy",
            "env.screen_size=32",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.encoder.features_dim=8",
            "algo.encoder.channels=4",
            "algo.actor.dense_units=8",
            "algo.critic.dense_units=8",
            "algo.per_rank_batch_size=4",
            "algo.learning_starts=4",
            "algo.total_steps=16",
            "buffer.size=256",
            "buffer.device=True",
            "mesh.devices=1",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    assert _ckpts(tmp_path), "no checkpoint written"


@pytest.mark.slow
@pytest.mark.parametrize("base", ["p2e_dv1", "p2e_dv2"])
def test_p2e_dv12_exploration_and_finetuning(tmp_path, base):
    from sheeprl_tpu.cli import evaluate

    args = [f"exp={base}_dummy", "algo.total_steps=32", "algo.learning_starts=16"]
    run(args + ["env=discrete_dummy"] + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    run(
        args
        + [
            "env=discrete_dummy",
            f"algo.name={base}_finetuning",
            f"checkpoint.exploration_ckpt_path={ckpts[-1]}",
            "buffer.load_from_exploration=True",
            "algo.total_steps=48",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    fntn_ckpts = _ckpts(tmp_path)
    assert len(fntn_ckpts) > len(ckpts)
    evaluate([f"checkpoint_path={fntn_ckpts[-1]}", "env.capture_video=False"])


def test_ppo_decoupled_dummy_env(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(
        [
            "exp=ppo_decoupled",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.total_steps=64",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    ckpts = _ckpts(tmp_path)
    assert ckpts
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


def test_sac_decoupled_dummy_env(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(
        [
            "exp=sac_decoupled",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=4",
            "algo.total_steps=16",
            "buffer.size=256",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    ckpts = _ckpts(tmp_path)
    assert ckpts
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


@pytest.mark.slow
def test_dreamer_v3_decoupled_rssm(tmp_path):
    run(
        DV3_ARGS
        + ["env=discrete_dummy", "algo.world_model.decoupled_rssm=True"]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )


def test_every_algorithm_has_evaluation():
    """Every registered entry point must have an evaluation entry, or
    ``sheeprl_tpu.eval`` dies at dispatch for that algorithm (reference registers an
    evaluate function per algo in ``sheeprl/__init__.py:18-47``)."""
    from sheeprl_tpu.cli import _import_algorithms
    from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry

    _import_algorithms()
    assert len(algorithm_registry) >= 17
    missing = set(algorithm_registry) - set(evaluation_registry)
    assert not missing, f"algorithms without a registered evaluation: {sorted(missing)}"


def test_agents_listing(capsys):
    from sheeprl_tpu.cli import agents

    agents()
    out = capsys.readouterr().out
    assert "dreamer_v3" in out and "sac_decoupled" in out
    assert "decoupled" in out.splitlines()[0]


def test_module_launchers_wired(tmp_path):
    """`python -m sheeprl_tpu` / `.eval` / `.registration` must resolve as modules
    (reference ships sheeprl.py / sheeprl_eval.py / sheeprl_model_manager.py
    launchers); a missing module file dies at interpreter start, before any test
    that imports the functions directly would notice."""
    import subprocess
    import sys

    for mod, needle in (
        ("sheeprl_tpu", "exp="),  # usage error mentions config selection
        ("sheeprl_tpu.eval", "checkpoint_path"),
        ("sheeprl_tpu.registration", "checkpoint_path"),
    ):
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        proc = subprocess.run(
            [sys.executable, "-m", mod],
            capture_output=True,
            text=True,
            timeout=120,
            cwd=repo_root,  # module resolution must not depend on pytest's cwd
            env={**os.environ, "SHEEPRL_TPU_QUIET": "1"},
        )
        blob = proc.stdout + proc.stderr
        assert proc.returncode != 0  # no args -> usage/validation error, not ImportError
        assert "No module named" not in blob, f"{mod} launcher missing: {blob[-500:]}"
        assert needle in blob, f"{mod} did not print its usage hint: {blob[-500:]}"


@pytest.mark.slow
def test_dreamer_v3_memmap_buffer_resume(tmp_path):
    """E2E with disk-backed (memmap) replay buffers + checkpoint + resume: the
    reference's default buffer mode (buffer.memmap=True) was only unit-tested; this
    drives it through the full loop including the buffer checkpoint round trip."""
    args = DV3_ARGS + ["env=discrete_dummy"]
    # memmap=True must come in extra: standard_args itself pins memmap=False earlier
    # in the list and the last override wins.
    extra = ["dry_run=False", "buffer.memmap=True", "buffer.checkpoint=True"]
    run(args + standard_args(tmp_path, extra=extra))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    # The buffer checkpoint stores memmap METADATA (not a copy), releasing file
    # ownership — the backing .memmap files must therefore survive run() for the
    # resume below to reattach to them.
    files = list(tmp_path.rglob("memmap_buffer/**/*.memmap"))
    assert files, "no memmap storage survived despite buffer.memmap=True + checkpoint"
    run(
        args
        + [f"checkpoint.resume_from={ckpts[-1]}", "algo.total_steps=48"]
        + standard_args(tmp_path, extra=extra)
    )


@pytest.mark.slow  # ~160s — the single heaviest tier-1 test; rides the nightly
# slow tier to protect the 870s tier-1 budget (same move as the PR-8 SAC
# round-trip; the DV3 model-parallel math stays covered by
# tests/test_parallel/test_dp_parity.py and the IR audit's sharded entries).
def test_dreamer_v3_tensor_parallel_cli(tmp_path):
    """Train DreamerV3 through the CLI with mesh.data=4 x mesh.model=2 on the 8-device
    CPU mesh — tensor parallelism as a pure config knob: batch on the data axis, wide
    kernels column-sharded over the model axis (the dryrun covers the jit; this covers
    the full loop incl. player, checkpointing, and eval on the TP params)."""
    from sheeprl_tpu.cli import evaluate

    args = DV3_ARGS + [
        "env=discrete_dummy",
        "mesh.data=4",
        "mesh.model=2",
        # the XS dummy model's 256-wide kernels already exceed shard_params' min_dim,
        # so TP engages with the preset sizes; batch 4 makes the data axis shard too
        # (the default 2 does not divide mesh.data=4 and would silently replicate)
        "algo.per_rank_batch_size=4",
    ]
    run(args + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


@pytest.mark.slow
def test_droq_evaluate_roundtrip(tmp_path):
    from sheeprl_tpu.cli import evaluate

    # droq shares SAC's dummy-env settings; only the exp differs
    run(["exp=droq"] + SAC_ARGS[1:] + standard_args(tmp_path, extra=["dry_run=False"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


@pytest.mark.slow
def test_ppo_recurrent_evaluate_roundtrip(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(
        [
            "exp=ppo_recurrent",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=8",
            "algo.per_rank_num_batches=2",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.rnn.lstm.hidden_size=8",
            "algo.mlp_layers=1",
            "algo.total_steps=32",
        ]
        + standard_args(tmp_path)
    )
    ckpts = _ckpts(tmp_path)
    assert ckpts
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


@pytest.mark.slow
def test_sac_ae_evaluate_roundtrip(tmp_path):
    from sheeprl_tpu.cli import evaluate

    run(
        [
            "exp=sac_ae",
            "env=continuous_dummy",
            "env.screen_size=32",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.encoder.features_dim=8",
            "algo.encoder.channels=4",
            "algo.actor.dense_units=8",
            "algo.critic.dense_units=8",
            "algo.per_rank_batch_size=4",
            "algo.learning_starts=4",
            "algo.total_steps=16",
            "buffer.size=256",
        ]
        + standard_args(tmp_path, extra=["dry_run=False"])
    )
    ckpts = _ckpts(tmp_path)
    assert ckpts
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])
