"""Fused scanned update blocks over the device-resident transition ring
(``data/device_buffer.py`` + ``utils/blocks.FusedRingDispatcher``).

CPU parity proof required by the device-replay work: a scanned K-step block must
be BIT-IDENTICAL to K sequential dispatches (per-step keys derive from
``fold_in(base_key, cumulative_step)``, so any chunk decomposition reproduces the
fused whole), and the dispatcher must issue exactly ONE jit call per block
(K→1 dispatch reduction).
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.config.core import compose
from sheeprl_tpu.data.device_buffer import DeviceTransitionRing
from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh
from sheeprl_tpu.utils.blocks import FusedRingDispatcher

OBS_DIM, ACT_DIM, BATCH = 5, 2, 4


def _ctx():
    return MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)


def _spaces():
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1.0, 1.0, (OBS_DIM,), np.float32)})
    act_space = gym.spaces.Box(-1.0, 1.0, (ACT_DIM,), np.float32)
    return obs_space, act_space


def _ring(n_envs=2, cap=32, steps=20, seed=0):
    rng = np.random.default_rng(seed)
    ring = DeviceTransitionRing(
        cap,
        n_envs,
        {
            "obs": ((OBS_DIM,), jnp.float32),
            "next_obs": ((OBS_DIM,), jnp.float32),
            "actions": ((ACT_DIM,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
    )
    for t in range(steps):
        ring.add_step(
            {
                "obs": rng.random((1, n_envs, OBS_DIM)).astype(np.float32),
                "next_obs": rng.random((1, n_envs, OBS_DIM)).astype(np.float32),
                "actions": rng.random((1, n_envs, ACT_DIM)).astype(np.float32),
                "rewards": rng.random((1, n_envs, 1)).astype(np.float32),
                "dones": np.zeros((1, n_envs, 1), np.float32),
            },
            t % cap,
            t,
        )
    return ring, min(steps, cap), steps


def _copy(tree):
    """Independent deep copy: dispatches DONATE the carry, so each compared path
    needs its own buffers (donation is live even on the virtual CPU mesh)."""
    return jax.tree.map(jnp.copy, tree)


def _assert_trees_equal(a, b, what):
    for pa, la in zip(jax.tree_util.tree_leaves_with_path(a), jax.tree.leaves(b)):
        path, leaf_a = pa
        np.testing.assert_array_equal(
            np.asarray(leaf_a), np.asarray(la), err_msg=f"{what}: {jax.tree_util.keystr(path)}"
        )


def test_sac_fused_block_bit_identical_to_sequential():
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import make_sac_fused_builder

    cfg = compose(
        overrides=[
            "exp=sac",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            f"algo.per_rank_batch_size={BATCH}",
        ]
    )
    ctx = _ctx()
    obs_space, act_space = _spaces()
    ring, filled, rows_added = _ring()
    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    actor_opt, critic_opt, alpha_opt, builder = make_sac_fused_builder(
        actor, critic, cfg, act_space, ring, BATCH
    )
    opt_state = {
        "actor": actor_opt.init(params["actor"]),
        "critic": critic_opt.init(params["critic"]),
        "alpha": alpha_opt.init(params["log_alpha"]),
    }
    carry0 = {"params": params, "opt_state": opt_state}
    base_key = jax.random.PRNGKey(11)
    K = 5

    fused = FusedRingDispatcher(builder, base_key=base_key)
    carry_fused = fused.dispatch(_copy(carry0), ring.arrays, filled, rows_added, K, 0)
    # The whole K-step block (sampling + K updates + EMA cadence) is ONE dispatch.
    assert fused.dispatch_count == 1

    seq = FusedRingDispatcher(builder, base_key=base_key)
    carry_seq = _copy(carry0)
    for g in range(K):
        carry_seq = seq.dispatch(carry_seq, ring.arrays, filled, rows_added, 1, g)
    assert seq.dispatch_count == K

    _assert_trees_equal(carry_fused, carry_seq, "sac fused-vs-sequential train state")


def test_sac_fused_block_chunk_decomposition_bit_identical():
    """Once the program cache is full, irregular sizes chunk into cached powers of
    two — the per-step fold_in key derivation keeps that bit-identical too."""
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import make_sac_fused_builder

    cfg = compose(
        overrides=[
            "exp=sac",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            f"algo.per_rank_batch_size={BATCH}",
        ]
    )
    ctx = _ctx()
    obs_space, act_space = _spaces()
    ring, filled, rows_added = _ring(seed=1)
    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    actor_opt, critic_opt, alpha_opt, builder = make_sac_fused_builder(
        actor, critic, cfg, act_space, ring, BATCH
    )
    opt_state = {
        "actor": actor_opt.init(params["actor"]),
        "critic": critic_opt.init(params["critic"]),
        "alpha": alpha_opt.init(params["log_alpha"]),
    }
    carry0 = {"params": params, "opt_state": opt_state}
    base_key = jax.random.PRNGKey(3)
    K = 5

    fused = FusedRingDispatcher(builder, base_key=base_key)
    carry_fused = fused.dispatch(_copy(carry0), ring.arrays, filled, rows_added, K, 0)

    # max_programs=1: after the first (2-step) program is cached, K=5 cannot
    # compile a new size and decomposes into power-of-two chunks instead.
    chunked = FusedRingDispatcher(builder, base_key=base_key, max_programs=1, max_chunk=4)
    warm = chunked.dispatch(_copy(carry0), ring.arrays, filled, rows_added, 2, 0)
    del warm
    assert list(chunked._blocks) == [(2, True)]
    carry_chunked = chunked.dispatch(_copy(carry0), ring.arrays, filled, rows_added, K, 0)
    assert chunked.dispatch_count > 2  # the K=5 block went out as several chunks
    assert all(k in (1, 2, 4) for (k, _) in chunked._blocks)

    _assert_trees_equal(carry_fused, carry_chunked, "sac fused-vs-chunked train state")


def test_droq_fused_block_bit_identical_and_one_dispatch():
    """DroQ's whole UTD block — K critic updates + the once-per-iteration actor
    update — is ONE dispatch, bit-identical to K critic-only dispatches followed
    by the actor tail."""
    from sheeprl_tpu.algos.droq.droq import DroQCriticEnsemble, make_droq_fused_builder
    from sheeprl_tpu.algos.sac.agent import SACActor

    cfg = compose(
        overrides=[
            "exp=droq",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            f"algo.per_rank_batch_size={BATCH}",
        ]
    )
    ctx = _ctx()
    obs_space, act_space = _spaces()
    ring, filled, rows_added = _ring(seed=2)

    actor = SACActor(act_dim=ACT_DIM, hidden_size=cfg.algo.actor.hidden_size, dtype=ctx.compute_dtype)
    critic = DroQCriticEnsemble(
        n_critics=cfg.algo.critic.n,
        hidden_size=cfg.algo.critic.hidden_size,
        dropout=cfg.algo.critic.dropout,
        dtype=ctx.compute_dtype,
    )
    dummy_obs, dummy_act = jnp.zeros((1, OBS_DIM)), jnp.zeros((1, ACT_DIM))
    params = {
        "actor": actor.init(ctx.rng(), dummy_obs),
        "critic": critic.init({"params": ctx.rng(), "dropout": ctx.rng()}, dummy_obs, dummy_act),
        "log_alpha": jnp.asarray(jnp.log(cfg.algo.alpha.alpha), dtype=jnp.float32),
    }
    params["critic_target"] = jax.tree.map(jnp.copy, params["critic"])

    actor_opt, critic_opt, alpha_opt, builder = make_droq_fused_builder(
        actor, critic, cfg, act_space, ring, BATCH
    )
    opt_state = {
        "actor": actor_opt.init(params["actor"]),
        "critic": critic_opt.init(params["critic"]),
        "alpha": alpha_opt.init(params["log_alpha"]),
    }
    carry0 = {"params": params, "opt_state": opt_state}
    base_key = jax.random.PRNGKey(17)
    K = 4

    fused = FusedRingDispatcher(builder, base_key=base_key, last_sensitive=True)
    carry_fused = fused.dispatch(_copy(carry0), ring.arrays, filled, rows_added, K, 0)
    # 20-critic-updates-+-actor-per-dispatch is the whole point: ONE jit call.
    assert fused.dispatch_count == 1

    # Sequential reference: K critic-only chunks, then the actor tail at the
    # block-closing cumulative count (the key-derivation contract).  Donated like
    # the dispatcher's blocks — donation changes XLA's compiled program, so a
    # non-donated reference would drift by one ulp.
    critic_block = jax.jit(builder(1, False), donate_argnums=(0,))
    actor_tail = jax.jit(builder(0, True), donate_argnums=(0,))
    carry_seq = _copy(carry0)
    for g in range(K):
        carry_seq, _ = critic_block(carry_seq, ring.arrays, filled, rows_added, base_key, g)
    carry_seq, _ = actor_tail(carry_seq, ring.arrays, filled, rows_added, base_key, K)

    _assert_trees_equal(carry_fused, carry_seq, "droq fused-vs-sequential train state")


def test_fused_block_metrics_carry_replay_age():
    """Health/replay_age_* are computed IN-JIT from the ring's stamp plane and ride
    the block's metrics pytree (no host-side sampling happens on the ring path)."""
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import make_sac_fused_builder

    cfg = compose(
        overrides=[
            "exp=sac",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            f"algo.per_rank_batch_size={BATCH}",
        ]
    )
    ctx = _ctx()
    obs_space, act_space = _spaces()
    ring, filled, rows_added = _ring(seed=4)
    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    actor_opt, critic_opt, alpha_opt, builder = make_sac_fused_builder(
        actor, critic, cfg, act_space, ring, BATCH
    )
    opt_state = {
        "actor": actor_opt.init(params["actor"]),
        "critic": critic_opt.init(params["critic"]),
        "alpha": alpha_opt.init(params["log_alpha"]),
    }
    block = jax.jit(builder(2, True))
    _, metrics = block(
        {"params": params, "opt_state": opt_state},
        ring.arrays,
        filled,
        rows_added,
        jax.random.PRNGKey(0),
        0,
    )
    assert "Health/replay_age_mean" in metrics and "Health/replay_age_max" in metrics
    assert 0.0 <= float(metrics["Health/replay_age_mean"]) <= float(metrics["Health/replay_age_max"])
    assert float(metrics["Health/replay_age_max"]) <= rows_added - 1
    for k in ("Loss/value_loss", "Loss/policy_loss", "Loss/alpha_loss"):
        assert k in metrics
