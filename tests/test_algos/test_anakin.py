"""Anakin training mode (``sheeprl_tpu/engine/anakin.py``): the ISSUE-6
correctness contracts.

* the fused PPO iteration's update is BIT-IDENTICAL to the standalone jitted
  ``PPOTrainFns.train_fn`` on the same collected batch (only the collection path
  changes);
* the scan carry (env states, ring + counters, PRNG key, params, opt state)
  round-trips through ``CheckpointManager`` and the CLI resume path continues a
  run mid-Anakin;
* the flight recorder stages a post-dispatch device-side COPY of the carry (the
  dispatch donates its input), and a strict-mode NaN crash dumps + replays;
* CLI e2e smokes for ``exp=ppo env=jax_cartpole algo.anakin=True`` and the SAC
  path on ``jax_pendulum``.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.config.core import compose
from sheeprl_tpu.envs.jax import make_jax_env
from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh

PPO_ANAKIN_ARGS = [
    "exp=ppo",
    "env=jax_cartpole",
    "algo.anakin=True",
    "algo.mlp_keys.encoder=[state]",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=8",
    "algo.update_epochs=1",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.encoder.mlp_features_dim=8",
]

SAC_ANAKIN_ARGS = [
    "exp=sac",
    "env=jax_pendulum",
    "algo.anakin=True",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=8",
    "algo.per_rank_batch_size=8",
    "algo.learning_starts=8",
    "algo.total_steps=64",
    "algo.anakin_steps_per_dispatch=8",
    "buffer.size=256",
]


def standard_args(tmp_path, extra=()):
    return [
        "dry_run=True",
        "env.num_envs=2",
        "env.capture_video=False",
        "checkpoint.every=1",
        "checkpoint.save_last=True",
        "metric.log_every=1",
        f"log_root={tmp_path}",
        "buffer.memmap=False",
        "algo.run_test=False",
        *extra,
    ]


def _ckpts(tmp_path):
    return sorted(tmp_path.rglob("ckpt_*"), key=lambda p: p.stat().st_mtime)


def _ppo_setup(num_envs=2, update_epochs=2):
    cfg = compose(
        overrides=PPO_ANAKIN_ARGS
        + [f"algo.update_epochs={update_epochs}", f"env.num_envs={num_envs}",
           "env.capture_video=False", "buffer.memmap=False"]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.engine.anakin import init_episode_stats, reset_envs

    env = make_jax_env("cartpole")
    env_params = env.default_params()
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    agent, params = build_agent(ctx, env.action_space(env_params), obs_space, cfg)
    fns = PPOTrainFns(ctx, agent, cfg, ["state"], 4)
    opt_state = ctx.replicate(fns.opt.init(params))
    env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(7))
    carry = {
        "params": params,
        "opt_state": opt_state,
        "env_state": env_state,
        "obs": obs0,
        "key": jax.random.PRNGKey(3),
        "episode_stats": init_episode_stats(num_envs),
    }
    return cfg, ctx, env, env_params, agent, fns, carry


def test_ppo_anakin_update_bit_identical_to_host_train_fn():
    """The acceptance contract: given the same collected batch and key, the fused
    Anakin iteration's update produces EXACTLY the host ``train_fn``'s params and
    metrics — only the collection path changed."""
    from sheeprl_tpu.engine.anakin import make_ppo_anakin_iteration

    cfg, ctx, env, env_params, agent, fns, carry = _ppo_setup()
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, "state", return_batch=True)
    new_carry, metrics, data, k_train = jax.jit(iteration)(carry, 0.2, 0.01)

    p2, _o2, m2 = fns.train_fn(
        carry["params"], carry["opt_state"], jax.device_get(data), k_train, 0.2, 0.01
    )
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(new_carry["params"]), jax.tree.leaves(p2)
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"params diverged at {jax.tree_util.keystr(path)}"
        )
    for k in m2:
        np.testing.assert_array_equal(np.asarray(metrics[k]), np.asarray(m2[k]), err_msg=k)


def test_ppo_anakin_carry_roundtrips_through_checkpoint_manager(tmp_path):
    """Scan-carry state (env states incl. NamedTuples, PRNG key, opt state,
    episode accumulators) survives a CheckpointManager save/load bit-exactly."""
    from sheeprl_tpu.checkpoint.manager import CheckpointManager

    cfg, ctx, env, env_params, agent, fns, carry = _ppo_setup()
    from sheeprl_tpu.engine.anakin import make_ppo_anakin_iteration

    dispatch = jax.jit(make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, "state"))
    carry, _metrics = dispatch(carry, 0.2, 0.0)  # a non-trivial mid-run carry

    mgr = CheckpointManager(tmp_path / "ckpts", keep_last=2)
    mgr.save(1, {"carry": carry, "update": 1, "policy_step": 16})
    template = jax.tree.map(lambda x: None, jax.device_get(carry))
    state = CheckpointManager.load(mgr.list_checkpoints()[-1], templates={"carry": jax.device_get(carry)})
    del template
    assert state["update"] == 1 and state["policy_step"] == 16
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(jax.device_get(carry)), jax.tree.leaves(state["carry"])
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"carry leaf {jax.tree_util.keystr(path)}"
        )


def test_sac_anakin_ring_counters_roundtrip(tmp_path):
    """SAC-side resume contract: ring arrays + rows_added/gstep counters live in
    the carry and restore exactly (the in-jit sampler derives from them)."""
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.data.device_buffer import STAMP_KEY, DeviceTransitionRing
    from sheeprl_tpu.engine.anakin import init_episode_stats, make_sac_anakin_dispatch, reset_envs

    cfg = compose(
        overrides=SAC_ANAKIN_ARGS
        + ["env.num_envs=2", "env.capture_video=False", "buffer.memmap=False"]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    env = make_jax_env("pendulum")
    env_params = env.default_params()
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    act_space = env.action_space(env_params)
    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    params = jax.tree.map(jnp.copy, params)
    ring = DeviceTransitionRing(
        16, 2, {"obs": ((3,), jnp.float32), "next_obs": ((3,), jnp.float32),
                "actions": ((1,), jnp.float32), "rewards": ((1,), jnp.float32),
                "dones": ((1,), jnp.float32)}
    )
    actor_opt, critic_opt, alpha_opt, builder = make_sac_anakin_dispatch(
        env, env_params, actor, critic, cfg, act_space, ring, 4
    )
    carry = {
        "params": params,
        "opt_state": {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        },
        "env_state": reset_envs(env, env_params, 2, jax.random.PRNGKey(0))[0],
        "obs": reset_envs(env, env_params, 2, jax.random.PRNGKey(0))[1],
        "ring": ring.arrays,
        "rows_added": jnp.zeros((), jnp.int32),
        "gstep": jnp.zeros((), jnp.int32),
        "key": jax.random.PRNGKey(1),
        "episode_stats": init_episode_stats(2),
    }
    dispatch = jax.jit(builder(5, 1, True), donate_argnums=(0,))
    carry, _metrics = dispatch(carry)
    assert int(jax.device_get(carry["rows_added"])) == 5
    assert int(jax.device_get(carry["gstep"])) == 5
    stamps = np.asarray(jax.device_get(carry["ring"][STAMP_KEY]))
    np.testing.assert_array_equal(stamps[:, :5, 0], np.broadcast_to(np.arange(5), (2, 5)))

    mgr = CheckpointManager(tmp_path / "ckpts", keep_last=1)
    mgr.save(5, {"carry": carry})
    state = CheckpointManager.load(mgr.list_checkpoints()[-1], templates={"carry": jax.device_get(carry)})
    assert int(state["carry"]["rows_added"]) == 5
    np.testing.assert_array_equal(
        np.asarray(state["carry"]["ring"][STAMP_KEY]), stamps
    )


def test_ppo_anakin_flight_recorder_stages_carry_copy():
    """Post-dispatch staging: the recorder holds a device-side COPY of the carry
    (the donated originals are dead), fetchable without error."""
    from sheeprl_tpu.engine.anakin import make_ppo_anakin_iteration, stage_carry
    from sheeprl_tpu.obs import flight_recorder

    cfg, ctx, env, env_params, agent, fns, carry = _ppo_setup()
    dispatch = jax.jit(
        make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, "state"), donate_argnums=(0,)
    )
    recorder = flight_recorder.FlightRecorder("/tmp/unused", capacity=16)
    carry, _metrics = dispatch(carry, 0.2, 0.0)
    stage_carry(recorder, carry, update=1, clip_coef=0.2, ent_coef=0.0)
    assert recorder.staged_updates == 1
    staged = recorder._staged["carry"]
    carry2, _metrics2 = dispatch(carry, 0.2, 0.0)  # donates the staged copy's source
    # the staged copy must still be alive and fetchable after the donation
    fetched = jax.device_get(staged["params"])
    assert all(np.isfinite(x).all() for x in jax.tree.leaves(fetched))
    del carry2


def test_ppo_anakin_cli_smoke_and_resume(tmp_path):
    run(PPO_ANAKIN_ARGS + ["algo.total_steps=32"] + standard_args(tmp_path))
    ckpts = _ckpts(tmp_path)
    assert ckpts, "no checkpoint written"
    run(
        PPO_ANAKIN_ARGS
        + ["algo.total_steps=32", f"checkpoint.resume_from={ckpts[-1]}"]
        + standard_args(tmp_path)
    )


def test_ppo_anakin_evaluate_roundtrip(tmp_path):
    """Anakin checkpoints store the scan carry; the eval entry digs the policy
    params out of it and runs the greedy episode through the host adapter."""
    from sheeprl_tpu.cli import evaluate

    run(PPO_ANAKIN_ARGS + ["algo.total_steps=32"] + standard_args(tmp_path))
    ckpts = _ckpts(tmp_path)
    assert ckpts
    evaluate([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


def test_sac_anakin_cli_smoke_and_resume(tmp_path):
    run(SAC_ANAKIN_ARGS + standard_args(tmp_path, extra=["dry_run=False", "checkpoint.every=16", "metric.log_every=16"]))
    ckpts = _ckpts(tmp_path)
    assert ckpts, "no checkpoint written"
    run(
        SAC_ANAKIN_ARGS
        + [f"checkpoint.resume_from={ckpts[-1]}", "algo.total_steps=96"]
        + standard_args(tmp_path, extra=["dry_run=False", "checkpoint.every=16", "metric.log_every=16"])
    )


def test_sac_anakin_rejects_fractional_replay_ratio(tmp_path):
    with pytest.raises(ValueError, match="integer algo.replay_ratio"):
        run(
            SAC_ANAKIN_ARGS
            + ["algo.replay_ratio=0.5"]
            + standard_args(tmp_path, extra=["dry_run=False"])
        )


def test_anakin_requires_jax_env(tmp_path):
    with pytest.raises(ValueError, match="on-device JAX environment"):
        run(
            [
                "exp=ppo",
                "env=discrete_dummy",
                "algo.anakin=True",
                "algo.mlp_keys.encoder=[state]",
                "algo.rollout_steps=8",
                "algo.per_rank_batch_size=8",
            ]
            + standard_args(tmp_path)
        )


def test_ppo_anakin_nan_injection_dumps_and_replays(tmp_path):
    """Strict-mode crash forensics mid-Anakin: injected NaN -> NonFiniteError ->
    blackbox dump with the staged carry -> replay re-executes the fused dispatch
    on CPU and reproduces the non-finite metrics."""
    from sheeprl_tpu.analysis.strict import NonFiniteError
    from sheeprl_tpu.obs import replay_blackbox

    with pytest.raises(NonFiniteError, match="inject_nan"):
        run(
            PPO_ANAKIN_ARGS
            + ["analysis.strict=True", "analysis.inject_nan=True"]
            + standard_args(tmp_path, extra=["checkpoint.every=0", "checkpoint.save_last=False"])
        )
    dumps = list(tmp_path.rglob("blackbox"))
    assert dumps, "no blackbox directory written"
    outputs, nonfinite = replay_blackbox.replay(dumps[0])
    assert nonfinite, "replay did not reproduce the injected non-finite metrics"


def test_anakin_exp_presets_compose():
    for exp in ("ppo_anakin", "sac_anakin"):
        cfg = compose(overrides=[f"exp={exp}"])
        assert cfg.algo.anakin and cfg.env.jax.enabled and cfg.env.jax.env_id
        assert cfg.algo.mlp_keys.encoder == ["state"]


def test_anakin_bench_smoke(capsys):
    """Tier-1 smoke of benchmarks/anakin_bench.py at tiny shapes: both rows print
    with the expected fields (the acceptance speedup is asserted only on real
    hardware runs, not on the shared CI box)."""
    import json
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "benchmarks"))
    try:
        import anakin_bench
    finally:
        sys.path.pop(0)
    anakin_bench.main(
        ["--num-envs", "8", "--steps", "64", "--host-steps", "16", "--rollout-steps", "8",
         "--ppo-envs", "4", "--iters", "2", "--host-envs", "2",
         "--members", "2", "--pop-envs", "4", "--pop-rollout", "4", "--pop-iters", "2",
         "--compile-bench", "0"]
    )
    rows = [json.loads(line) for line in capsys.readouterr().out.splitlines() if line.strip()]
    by_metric = {r["metric"]: r for r in rows}
    assert set(by_metric) == {
        "anakin_cartpole_steps_per_sec",
        "anakin_ppo_grad_steps_per_sec",
        "anakin_population_steps_per_sec",
    }
    row = by_metric["anakin_cartpole_steps_per_sec"]
    assert row["value"] > 0 and row["speedup_vs_host"] > 0
    assert "host_sync_vector_steps_per_sec" in row and "speedup_vs_raw_gym_saturated" in row
    assert by_metric["anakin_ppo_grad_steps_per_sec"]["value"] > 0
    pop = by_metric["anakin_population_steps_per_sec"]
    assert pop["value"] > 0 and pop["members"] == 2
    assert pop["per_member_efficiency"] > 0 and pop["single_member_steps_per_sec"] > 0
