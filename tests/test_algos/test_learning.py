"""Learning-signal tests (VERDICT r1 item 7): smoke tests alone cannot catch a
sign-flipped advantage or KL — these assert that learning actually HAPPENS.

* PPO on CartPole-v1 must clearly beat a random policy within a small step budget;
* SAC on Pendulum-v1 must clearly beat a random policy within a small step budget;
* a Dreamer (V1/V2/V3) world-model loss must strictly decrease when the jitted train
  step is iterated on a fixed synthetic batch.
"""

import glob

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.cli import run

# Learning-to-reward runs take minutes each — slow tier (run with -m slow).
pytestmark = pytest.mark.slow


def _tb_scalar(log_root, tag):
    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    runs = sorted(glob.glob(f"{log_root}/**/version_*", recursive=True))
    assert runs, "no run dir written"
    ea = EventAccumulator(runs[-1])
    ea.Reload()
    assert tag in ea.Tags()["scalars"], f"{tag} not logged"
    return [s.value for s in ea.Scalars(tag)]


def test_ppo_cartpole_learns(tmp_path):
    """Random CartPole policy scores ~20; a correctly-signed PPO must far exceed it."""
    run(
        [
            "exp=ppo",
            "env=gym",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=128",
            "algo.per_rank_batch_size=64",
            "algo.update_epochs=4",
            "algo.dense_units=64",
            "algo.mlp_layers=2",
            "algo.total_steps=20480",
            "env.num_envs=4",
            "env.sync_env=True",
            "env.capture_video=False",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            "metric.log_every=512",
            f"log_root={tmp_path}",
            "buffer.memmap=False",
        ]
    )
    test_reward = _tb_scalar(tmp_path, "Test/cumulative_reward")[-1]
    train_rewards = _tb_scalar(tmp_path, "Rewards/rew_avg")
    best = max(max(train_rewards), test_reward)
    assert best >= 100.0, f"PPO failed to learn CartPole: best avg reward {best:.1f} (< 100)"


def _world_model_loss_curve(algo: str, steps: int = 25):
    """Iterate the jitted train step on one synthetic batch; return the WM losses."""
    import gymnasium as gym

    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh

    cfg = compose(
        overrides=[
            f"exp={algo}_dummy",
            "algo.per_rank_batch_size=4",
            "algo.per_rank_sequence_length=8",
        ]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    screen = cfg.env.screen_size if algo == "dreamer_v3" else 64
    obs_space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (3, screen, screen), np.uint8),
            "state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32),
        }
    )
    actions_dim = (3,)

    if algo == "dreamer_v3":
        from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
        from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
        from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    elif algo == "dreamer_v2":
        from sheeprl_tpu.algos.dreamer_v2.agent import build_agent
        from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import make_train_step
    else:
        from sheeprl_tpu.algos.dreamer_v1.agent import build_agent
        from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_train_step

    world_model, actor, critic, params, *_ = build_agent(ctx, actions_dim, False, cfg, obs_space)

    T, B = 8, 4
    rng = np.random.default_rng(0)
    # A learnable (low-entropy, structured) synthetic sequence.
    base = rng.integers(0, 64, (1, 1, 3, screen, screen), dtype=np.uint8)
    data = {
        "rgb": jnp.asarray(np.broadcast_to(base, (T, B, 3, screen, screen)).copy()),
        "state": jnp.asarray(rng.random((T, B, 4)).astype(np.float32)),
        "actions": jnp.asarray(rng.random((T, B, 3)).astype(np.float32)),
        "rewards": jnp.ones((T, B, 1), jnp.float32),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }

    losses = []
    key = jax.random.PRNGKey(0)
    if algo == "dreamer_v3":
        train_step, init_opt = make_train_step(world_model, actor, critic, cfg, ["rgb"], ["state"], {})
        opt_states = init_opt(params)
        moments = init_moments()
        train_jit = jax.jit(train_step)
        for _ in range(steps):
            key, sub = jax.random.split(key)
            params, opt_states, moments, metrics = train_jit(
                params, opt_states, moments, data, sub, jnp.asarray(True)
            )
            losses.append(float(metrics["Loss/world_model_loss"]))
    elif algo == "dreamer_v2":
        train_step, init_opt = make_train_step(world_model, actor, critic, cfg, ["rgb"], ["state"])
        opt_states = init_opt(params)
        train_jit = jax.jit(train_step)
        for _ in range(steps):
            key, sub = jax.random.split(key)
            params, opt_states, metrics = train_jit(params, opt_states, data, sub, jnp.asarray(True))
            losses.append(float(metrics["Loss/world_model_loss"]))
    else:
        train_step, init_opt = make_train_step(world_model, actor, critic, cfg, ["rgb"], ["state"])
        opt_states = init_opt(params)
        train_jit = jax.jit(train_step)
        for _ in range(steps):
            key, sub = jax.random.split(key)
            params, opt_states, metrics = train_jit(params, opt_states, data, sub)
            losses.append(float(metrics["Loss/world_model_loss"]))
    return losses


@pytest.mark.parametrize("algo", ["dreamer_v3", "dreamer_v2", "dreamer_v1"])
def test_dreamer_world_model_loss_decreases(algo):
    losses = _world_model_loss_curve(algo)
    assert np.isfinite(losses).all(), f"non-finite world-model loss: {losses}"
    first, last = np.mean(losses[:3]), np.mean(losses[-3:])
    assert last < first, f"{algo} world-model loss did not decrease: {first:.2f} -> {last:.2f}"


_LINE_MDP_TINY = [
    "exp=dreamer_v3_dummy",
    "env=line_dummy",
    "algo.dense_units=64",
    "algo.mlp_layers=2",
    "algo.world_model.recurrent_model.recurrent_state_size=64",
    "algo.world_model.transition_model.hidden_size=64",
    "algo.world_model.representation_model.hidden_size=64",
    "algo.world_model.discrete_size=8",
    "algo.world_model.stochastic_size=8",
    "algo.horizon=8",
    "algo.per_rank_sequence_length=16",
    "algo.learning_starts=128",
    "algo.replay_ratio=1",
    "algo.actor.optimizer.lr=3e-4",
    "algo.critic.optimizer.lr=3e-4",
    "env.num_envs=4",
    "env.sync_env=True",
    "env.capture_video=False",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "metric.log_every=128",
    "buffer.size=10000",
    "buffer.memmap=False",
]


def test_dreamer_v3_actor_learns_toy_mdp(tmp_path):
    """Imagination-path learning (VERDICT r2 item 5): on the LineWalk MDP (random walk
    ≲1.5, optimal 12) the DV3 ACTOR must improve measured return — a sign flip in
    λ-returns, moments normalization, or the REINFORCE objective fails this even
    though every world-model-loss test passes."""
    run(
        _LINE_MDP_TINY
        + [
            "algo.cnn_keys.encoder=[]",
            "algo.mlp_keys.encoder=[state]",
            "algo.per_rank_batch_size=8",
            "algo.total_steps=1280",
            "algo.world_model.optimizer.lr=4e-4",
            f"log_root={tmp_path}",
        ]
    )
    test_reward = _tb_scalar(tmp_path, "Test/cumulative_reward")[-1]
    train_rewards = _tb_scalar(tmp_path, "Rewards/rew_avg")
    best = max(max(train_rewards), test_reward)
    assert best >= 6.0, f"DV3 actor failed to learn the toy MDP: best return {best:.1f} (< 6)"
    assert max(train_rewards[-2:] + [test_reward]) > np.mean(train_rewards[:2]) + 2.0, (
        f"no improvement over the start: {train_rewards} / test {test_reward}"
    )


def test_dreamer_v3_learns_from_pixels(tmp_path):
    """Pixel learning (VERDICT r2 item 1): the LineWalk reward is a function of the
    VISIBLE state only (mlp encoder off), so return can improve only if the whole
    pixels → world model → imagination → policy loop works."""
    run(
        _LINE_MDP_TINY
        + [
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "env.screen_size=32",
            "algo.world_model.encoder.cnn_channels_multiplier=8",
            "algo.per_rank_batch_size=4",
            "algo.total_steps=768",
            "algo.world_model.optimizer.lr=5e-4",
            f"log_root={tmp_path}",
        ]
    )
    test_reward = _tb_scalar(tmp_path, "Test/cumulative_reward")[-1]
    train_rewards = _tb_scalar(tmp_path, "Rewards/rew_avg")
    best = max(max(train_rewards), test_reward)
    assert best >= 6.0, f"DV3 failed to learn from pixels: best return {best:.1f} (< 6)"


def test_sac_pendulum_learns(tmp_path):
    """Random Pendulum-v1 policy averages about -1200/episode; a correctly-signed SAC
    (critic TD target, reparameterized actor, alpha) must clearly beat that within a
    small step budget."""
    run(
        [
            "exp=sac",
            "env=gym",
            "env.id=Pendulum-v1",
            "algo.mlp_keys.encoder=[state]",
            "algo.total_steps=6144",
            "algo.learning_starts=512",
            "algo.replay_ratio=1",
            "algo.per_rank_batch_size=128",
            "algo.dense_units=64",
            "algo.mlp_layers=2",
            "env.num_envs=4",
            "env.sync_env=True",
            "env.capture_video=False",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            "metric.log_every=512",
            f"log_root={tmp_path}",
            "buffer.size=50000",
            "buffer.memmap=False",
        ]
    )
    test_reward = _tb_scalar(tmp_path, "Test/cumulative_reward")[-1]
    train_rewards = _tb_scalar(tmp_path, "Rewards/rew_avg")
    best = max(max(train_rewards), test_reward)
    assert best >= -900.0, f"SAC failed to learn Pendulum: best avg reward {best:.1f} (< -900)"
