"""In-jit training-health diagnostics: correctness, gating, overhead, zero host syncs."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.config.core import DotDict
from sheeprl_tpu.obs.health import diagnostics, health_enabled, health_metrics, replay_age_metrics


def _cfg(health=True, inject=False):
    return DotDict.wrap({"obs": {"health": health}, "analysis": {"inject_nan": inject}})


# ------------------------------------------------------------------ correctness
def test_module_norms_match_optax_global_norm():
    grads = {"actor": {"w": jnp.full((4, 4), 2.0)}, "critic": {"w": jnp.full((3,), -1.0)}}
    out = diagnostics(grads=grads)
    np.testing.assert_allclose(out["Health/grad_norm/actor"], float(optax.global_norm(grads["actor"])), rtol=1e-6)
    np.testing.assert_allclose(out["Health/grad_norm/critic"], np.sqrt(3.0), rtol=1e-6)
    assert float(out["Health/grad_finite_frac"]) == 1.0


def test_single_key_wrappers_are_unwrapped():
    # flax-style {"params": {...}} groups by the real module names
    tree = {"params": {"encoder": {"w": jnp.ones((2,))}, "head": {"w": jnp.ones((2,))}}}
    out = diagnostics(params=tree)
    assert set(out) == {"Health/param_norm/encoder", "Health/param_norm/head"}


def test_update_ratio():
    params = {"m": {"w": jnp.full((4,), 2.0)}, "n": {"w": jnp.full((4,), 1.0)}}  # m norm 4
    updates = {"m": {"w": jnp.full((4,), 0.2)}, "n": {"w": jnp.full((4,), 0.1)}}  # m norm 0.4
    out = diagnostics(params=params, updates=updates)
    np.testing.assert_allclose(float(out["Health/update_ratio/m"]), 0.1, rtol=1e-5)
    np.testing.assert_allclose(float(out["Health/update_ratio/n"]), 0.1, rtol=1e-5)


def test_finite_fraction_counts_nans():
    grads = {"m": {"w": jnp.asarray([1.0, jnp.nan, jnp.inf, 4.0])}}
    out = diagnostics(grads=grads)
    np.testing.assert_allclose(float(out["Health/grad_finite_frac"]), 0.5)


def test_aux_scalars_are_meaned():
    out = diagnostics(aux={"policy_entropy": jnp.asarray([1.0, 3.0]), "q_mean": 2.0})
    assert float(out["Health/policy_entropy"]) == 2.0
    assert float(out["Health/q_mean"]) == 2.0


# ------------------------------------------------------------------ gating
def test_health_metrics_gate():
    metrics = {"Loss/x": jnp.float32(1.0)}
    grads = {"m": {"w": jnp.ones((2,))}, "n": {"w": jnp.ones((2,))}}
    off = health_metrics(_cfg(health=False), metrics, grads=grads)
    assert set(off) == {"Loss/x"}
    on = health_metrics(_cfg(health=True), metrics, grads=grads)
    assert "Health/grad_norm/m" in on and "Loss/x" in on
    assert health_enabled(None) is False and health_enabled({}) is False


def test_inject_nan_poisons_one_leaf():
    out = health_metrics(_cfg(health=False, inject=True), {"Loss/x": jnp.float32(1.0)})
    assert not np.isfinite(np.asarray(out["Health/inject_nan"]))
    clean = health_metrics(_cfg(health=False, inject=False), {"Loss/x": jnp.float32(1.0)})
    assert "Health/inject_nan" not in clean


def test_replay_age_metrics_duck_typing():
    class WithAges:
        def sample_age_metrics(self):
            return {"Health/replay_age_mean": 3.0}

    assert replay_age_metrics(WithAges()) == {"Health/replay_age_mean": 3.0}
    assert replay_age_metrics(object()) == {}


# ------------------------------------------------------------------ microbench
def _make_step(with_health):
    """A PPO-shaped update: scan over minibatches of an MLP policy+value loss."""
    cfg = _cfg(health=with_health)
    layers = [256, 256, 256, 1]
    key = jax.random.PRNGKey(0)
    params = {}
    dim = 128
    for i, width in enumerate(layers):
        key, k = jax.random.split(key)
        params[f"layer_{i}"] = {
            "w": jax.random.normal(k, (dim, width)) * 0.05,
            "b": jnp.zeros(width),
        }
        dim = width
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def forward(p, x):
        for i in range(len(layers)):
            x = x @ p[f"layer_{i}"]["w"] + p[f"layer_{i}"]["b"]
            if i < len(layers) - 1:
                x = jax.nn.tanh(x)
        return x

    def loss_fn(p, mb):
        return jnp.mean((forward(p, mb["x"]) - mb["y"]) ** 2)

    @jax.jit
    def step(p, o, batch):
        def mb_step(carry, mb):
            p, o = carry
            loss, grads = jax.value_and_grad(loss_fn)(p, mb)
            updates, o = opt.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            metrics = {"Loss/loss": loss}
            metrics = health_metrics(cfg, metrics, grads=grads, params=p, updates=updates)
            return (p, o), metrics

        (p, o), metrics = jax.lax.scan(mb_step, (p, o), batch)
        return p, o, jax.tree.map(jnp.mean, metrics)

    # Norm cost is O(params); fwd/bwd is O(batch x params) — the minibatch size is
    # what sets the diagnostics/compute ratio, so use a realistically large one.
    batch = {
        "x": jax.random.normal(jax.random.PRNGKey(1), (2, 8192, 128)),
        "y": jax.random.normal(jax.random.PRNGKey(2), (2, 8192, 1)),
    }
    return step, params, opt_state, batch


def _min_time(step, params, opt_state, batch, repeats=8):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        p, o, m = step(params, opt_state, batch)
        jax.block_until_ready((p, m))
        best = min(best, time.perf_counter() - t0)
    return best


def test_health_overhead_and_no_host_transfers():
    """Acceptance microbench: health diagnostics add <=2% to the jitted train-step
    time, and the diagnostics-enabled step performs ZERO host transfers (the
    Health/* scalars ride the metrics pytree the step already returns)."""
    step_off, params, opt_state, batch = _make_step(with_health=False)
    step_on, params_on, opt_state_on, batch_on = _make_step(with_health=True)

    # warmup/compile both
    out_off = step_off(params, opt_state, batch)
    out_on = step_on(params_on, opt_state_on, batch_on)
    jax.block_until_ready((out_off, out_on))
    assert any(k.startswith("Health/") for k in out_on[2]), "diagnostics missing from step output"

    # Zero per-step host syncs: with transfers disallowed, the health-enabled
    # step must still execute (inputs already committed to device).
    params_dev, opt_dev, batch_dev = jax.device_put((params_on, opt_state_on, batch_on))
    jax.block_until_ready((params_dev, opt_dev, batch_dev))
    with jax.transfer_guard("disallow"):
        res = step_on(params_dev, opt_dev, batch_dev)
    jax.block_until_ready(res)

    # Wall-clock overhead: interleaved rounds of min-of-N, best round taken —
    # shared-CI scheduler noise on a single compiled step is +-2-3%, well above
    # the true diagnostics cost, so the upper bound is asserted on the best
    # pairing (a real regression inflates EVERY round, so it still trips).
    overheads = []
    for _ in range(3):
        t_off = _min_time(step_off, params, opt_state, batch, repeats=6)
        t_on = _min_time(step_on, params_on, opt_state_on, batch_on, repeats=6)
        overheads.append((t_on - t_off) / t_off)
    overhead = min(overheads)
    assert overhead <= 0.02, (
        f"health diagnostics overhead {overhead * 100:.2f}% > 2% "
        f"(rounds: {[f'{o * 100:.2f}%' for o in overheads]})"
    )
