"""Flight-recorder e2e: injected NaN -> strict crash -> blackbox dump -> replay repro.

The acceptance path for the crash-forensics pipeline: a CPU smoke run with
``analysis.strict=True analysis.inject_nan=True`` must (a) die with
``NonFiniteError`` at the update boundary, (b) leave a complete
``<log_dir>/blackbox/`` dump, and (c) have ``python -m
sheeprl_tpu.obs.replay_blackbox`` re-execute the dumped update step and reproduce
the non-finite output from the dumped batch + train state alone.
"""

import json
import os

import pytest

from sheeprl_tpu.analysis.strict import NonFiniteError
from sheeprl_tpu.cli import run
from sheeprl_tpu.obs import replay_blackbox


def _crash_args(tmp_path, extra, dry_run=True):
    return [
        f"dry_run={dry_run}",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
        "metric.log_every=1",
        f"log_root={tmp_path}",
        "buffer.memmap=False",
        "analysis.strict=True",
        "analysis.inject_nan=True",
        "algo.run_test=False",
        *extra,
    ]


def _find_dump(tmp_path):
    dumps = list(tmp_path.rglob("blackbox"))
    assert dumps, "no blackbox directory written"
    return dumps[0]


def _check_dump_complete(dump):
    assert (dump / "events.jsonl").is_file()
    assert (dump / "config.yaml").is_file()
    assert (dump / "state" / "ckpt_0" / "manifest.pkl").is_file()
    meta = json.loads((dump / "meta.json").read_text())
    assert meta["staged_state"] is True
    assert meta["replay_target"]
    assert meta["exception"]["type"] == "NonFiniteError"
    assert meta["config_fingerprint"] and meta.get("jax_version")
    events = [json.loads(line) for line in (dump / "events.jsonl").read_text().splitlines()]
    assert any(e["kind"] == "nonfinite" for e in events)
    return meta


def test_ppo_nan_injection_dumps_and_replays(tmp_path):
    with pytest.raises(NonFiniteError, match="inject_nan"):
        run(
            _crash_args(
                tmp_path,
                [
                    "exp=ppo",
                    "env=discrete_dummy",
                    "algo.mlp_keys.encoder=[state]",
                    "algo.rollout_steps=8",
                    "algo.per_rank_batch_size=8",
                    "algo.update_epochs=1",
                    "algo.dense_units=8",
                    "algo.mlp_layers=1",
                    "algo.encoder.mlp_features_dim=8",
                ],
            )
        )
    dump = _find_dump(tmp_path)
    meta = _check_dump_complete(dump)
    assert meta["algo"] == "ppo"

    outputs, nonfinite = replay_blackbox.replay(dump)
    assert nonfinite, f"replay did not reproduce the non-finite output: {outputs}"
    assert any("inject_nan" in path for path in nonfinite)


def test_replay_cli_reports_reproduction(tmp_path, capsys):
    with pytest.raises(NonFiniteError):
        run(
            _crash_args(
                tmp_path,
                [
                    "exp=ppo",
                    "env=discrete_dummy",
                    "algo.mlp_keys.encoder=[state]",
                    "algo.rollout_steps=8",
                    "algo.per_rank_batch_size=8",
                    "algo.update_epochs=1",
                    "algo.dense_units=8",
                    "algo.mlp_layers=1",
                    "algo.encoder.mlp_features_dim=8",
                ],
            )
        )
    dump = _find_dump(tmp_path)
    assert replay_blackbox.main([str(dump)]) == 0
    out = capsys.readouterr().out
    assert "NON-FINITE REPRODUCED" in out
    assert "NonFiniteError" in out  # original failure echoed from meta.json


@pytest.mark.slow
def test_dreamer_v3_nan_injection_dumps_and_replays(tmp_path):
    with pytest.raises(NonFiniteError, match="inject_nan"):
        run(
            _crash_args(
                tmp_path,
                [
                    "exp=dreamer_v3_dummy",
                    "env=discrete_dummy",
                    "algo.total_steps=32",
                    "algo.learning_starts=16",
                ],
                # dry_run skips the prefill the sequence sampler needs: run the
                # real (still tiny) loop so a gradient block actually dispatches.
                dry_run=False,
            )
        )
    dump = _find_dump(tmp_path)
    meta = _check_dump_complete(dump)
    assert meta["algo"] == "dreamer_v3"

    outputs, nonfinite = replay_blackbox.replay(dump)
    assert nonfinite, f"replay did not reproduce the non-finite output: {outputs}"
    assert any("inject_nan" in path for path in nonfinite)


def test_clean_run_leaves_no_blackbox(tmp_path):
    run(
        [
            "exp=ppo",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "dry_run=True",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            "metric.log_every=1",
            f"log_root={tmp_path}",
            "buffer.memmap=False",
            "algo.run_test=False",
        ]
    )
    assert not list(tmp_path.rglob("blackbox")), "clean run must not dump a black box"
    from sheeprl_tpu.obs import flight_recorder

    assert flight_recorder.get_active() is None, "recorder leaked across runs"
