"""Span tracer: nesting, Chrome-trace export format, percentiles, thread tracks."""

import json
import threading
import time

import pytest

from sheeprl_tpu.obs import tracer as tr
from sheeprl_tpu.obs.tracer import SpanTracer, span, trace_span
from sheeprl_tpu.utils.timer import timer


@pytest.fixture()
def tracer():
    t = SpanTracer(rank=0)
    prev = tr.set_active(t)
    yield t
    tr.set_active(prev)


def _x_events(tracer):
    return [e for e in tracer.chrome_trace()["traceEvents"] if e["ph"] == "X"]


def test_span_nesting_depth_and_order(tracer):
    with span("outer"):
        with span("inner"):
            time.sleep(0.001)
    events = {e["name"]: e for e in _x_events(tracer)}
    assert set(events) == {"outer", "inner"}
    assert events["inner"]["args"]["depth"] == 1
    assert events["outer"]["args"]["depth"] == 0
    # the child slice lies inside the parent slice
    assert events["outer"]["ts"] <= events["inner"]["ts"]
    assert events["inner"]["ts"] + events["inner"]["dur"] <= events["outer"]["ts"] + events["outer"]["dur"] + 1e-3


def test_chrome_trace_is_valid_json_with_metadata(tracer, tmp_path):
    with span("Time/phase"):
        pass
    path = tracer.export_chrome_trace(str(tmp_path / "trace.json"))
    doc = json.loads(open(path).read())
    assert doc["displayTimeUnit"] == "ms"
    phs = {e["ph"] for e in doc["traceEvents"]}
    assert phs == {"M", "X"}  # metadata + complete events, the Perfetto-loadable subset
    x = [e for e in doc["traceEvents"] if e["ph"] == "X"][0]
    assert x["pid"] == 0 and x["dur"] >= 0 and "ts" in x and x["cat"] == "sheeprl_tpu"


def test_timer_blocks_become_spans(tracer):
    with timer("Time/env_interaction_time"):
        with timer("Time/phase_player"):
            pass
    names = {e["name"] for e in _x_events(tracer)}
    assert names == {"Time/env_interaction_time", "Time/phase_player"}
    # and the flat timer registry still accumulates independently
    assert "Time/env_interaction_time" in timer.to_dict(reset=True)


def test_decorator_and_percentiles(tracer):
    @trace_span("Time/fn")
    def fn(x):
        return x + 1

    for i in range(10):
        assert fn(i) == i + 1
    stats = tracer.percentiles(reset=True)["Time/fn"]
    assert stats["count"] == 10
    assert 0 <= stats["p50"] <= stats["p95"] <= stats["p99"]
    # reset=True drained the histogram
    assert tracer.percentiles() == {}


def test_threads_get_separate_tracks(tracer):
    def work():
        with span("Time/worker"):
            time.sleep(0.001)

    threads = [threading.Thread(target=work) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with span("Time/main"):
        pass
    tids = {e["tid"] for e in _x_events(tracer)}
    assert len(tids) == 3  # two workers + main


def test_no_active_tracer_is_noop():
    assert tr.get_active() is None
    with span("ignored"):
        pass
    with timer("Time/ignored"):
        pass

    @trace_span("ignored")
    def fn():
        return 42

    assert fn() == 42
    timer.reset()


def test_max_events_bounded():
    t = SpanTracer(rank=0, max_events=5)
    prev = tr.set_active(t)
    try:
        for _ in range(10):
            with span("s"):
                pass
    finally:
        tr.set_active(prev)
    assert len(t) == 5
    assert t.dropped_events == 5
    # histograms keep feeding past the event cap
    assert t.percentiles()["s"]["count"] == 10
