"""Flight recorder units: ring bounds/rotation, thread safety, blackbox dumps."""

import json
import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.obs import flight_recorder as fr


@pytest.fixture(autouse=True)
def _no_active_recorder():
    prev = fr.install(None)
    yield
    fr.install(prev)


# ------------------------------------------------------------------ ring buffer
def test_ring_is_bounded_and_keeps_the_tail():
    r = fr.FlightRecorder("/tmp/unused", capacity=16)
    for i in range(100):
        r.record("tick", i=i)
    assert len(r) == 16
    assert r.total_recorded == 100
    tail = r.events()
    assert [e["i"] for e in tail] == list(range(84, 100))
    assert [e["i"] for e in r.events(last=4)] == [96, 97, 98, 99]


def test_ring_rotation_preserves_order_across_wraps():
    r = fr.FlightRecorder("/tmp/unused", capacity=4)
    for i in range(11):
        r.record("e", i=i)
    assert [e["i"] for e in r.events()] == [7, 8, 9, 10]


def test_record_event_is_noop_without_active_recorder():
    fr.record_event("orphan", x=1)  # must not raise
    assert fr.get_active() is None
    assert fr.dump_active("crash") is None


def test_install_returns_previous():
    a = fr.FlightRecorder("/tmp/a")
    b = fr.FlightRecorder("/tmp/b")
    assert fr.install(a) is None
    assert fr.install(b) is a
    fr.record_event("x")
    assert len(b) == 1 and len(a) == 0


def test_thread_safety_under_concurrent_records():
    r = fr.FlightRecorder("/tmp/unused", capacity=256)
    n_threads, per_thread = 8, 500

    def worker(tid):
        for i in range(per_thread):
            r.record("t", tid=tid, i=i)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r.total_recorded == n_threads * per_thread
    assert len(r) == 256
    for event in r.events():  # every entry intact, no torn writes
        assert event["kind"] == "t" and 0 <= event["i"] < per_thread


def test_jsonable_payloads():
    r = fr.FlightRecorder("/tmp/unused")
    r.record("x", f=float("nan"), arr=np.float32(2.5), big=np.arange(3), s="ok", none=None)
    e = r.events()[-1]
    json.dumps(e)  # everything JSON-serializable
    assert e["arr"] == 2.5 and e["s"] == "ok" and e["none"] is None


# ------------------------------------------------------------------ dumps
def test_dump_writes_events_meta_and_staged_state(tmp_path):
    r = fr.FlightRecorder(str(tmp_path), capacity=64, keep_events=8, algo="unittest",
                          cfg={"seed": 1, "algo": {"name": "unittest"}})
    for i in range(30):
        r.record("tick", i=i)
    r.arm_replay("some.module:replay_fn", note="static")
    r.stage_step(
        batch={"obs": jnp.ones((4, 3))},
        carry={"params": {"w": jnp.zeros((2, 2))}},
        scalars={"update": 7},
    )
    try:
        raise ValueError("boom")
    except ValueError as exc:
        out = r.dump("crash", exc)

    assert out == str(tmp_path / "blackbox")
    events = [json.loads(line) for line in open(os.path.join(out, "events.jsonl"))]
    assert len(events) == 8 and events[-1]["i"] == 29
    meta = json.load(open(os.path.join(out, "meta.json")))
    assert meta["algo"] == "unittest"
    assert meta["replay_target"] == "some.module:replay_fn"
    assert meta["staged_state"] is True
    assert meta["exception"]["type"] == "ValueError" and "boom" in meta["exception"]["message"]
    assert meta["config_fingerprint"]

    from sheeprl_tpu.checkpoint.manager import CheckpointManager

    state = CheckpointManager.load(os.path.join(out, "state", "ckpt_0"))
    assert state["scalars"]["update"] == 7
    assert state["statics"]["note"] == "static"
    np.testing.assert_array_equal(np.asarray(state["batch"]["obs"]), np.ones((4, 3)))


def test_first_dump_wins(tmp_path):
    r = fr.FlightRecorder(str(tmp_path), keep_events=4)
    r.record("a")
    first = r.dump("crash")
    r.record("b")
    second = r.dump("crash")
    assert first == second
    events = [json.loads(line) for line in open(os.path.join(first, "events.jsonl"))]
    assert [e["kind"] for e in events] == ["a"]


def test_stage_step_replaces_previous():
    r = fr.FlightRecorder("/tmp/unused")
    r.stage_step(batch=1)
    r.stage_step(batch=2)
    assert r.staged_updates == 2
    assert r._staged == {"batch": 2}
