"""Performance attribution plane (PR-19): cost-model registry + instrument
wrapper, goodput ledger on synthetic timelines, EWMA regression watchdog
exactly-once semantics, MFU agreement with ``bench.py``, and the monitor e2e
(perf_report.json + forced slowdown -> ONE auto-capture + ONE perf_regression
flight-recorder event)."""

import importlib.util
import json
import math
import pathlib
import time

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.obs import flight_recorder as flight_recorder_mod
from sheeprl_tpu.obs import perf
from sheeprl_tpu.obs.monitor import TrainingMonitor
from sheeprl_tpu.obs.perf import (
    GOODPUT_CATEGORIES,
    GoodputLedger,
    PerfPlane,
    StepTimeWatchdog,
)

REPO = pathlib.Path(__file__).resolve().parents[2]


@pytest.fixture(autouse=True)
def _clean_registry():
    perf.reset()
    yield
    perf.reset()
    flight_recorder_mod.install(None)


def _assert_sums_to_one(fractions):
    assert set(fractions) == set(GOODPUT_CATEGORIES)
    assert math.isclose(sum(fractions.values()), 1.0, abs_tol=1e-9), fractions
    assert all(f >= 0.0 for f in fractions.values()), fractions


# ------------------------------------------------------------- goodput ledger
def test_goodput_clean_run():
    """Compute-dominated window: goodput ~= compute + env, remainder -> other."""
    with jax.transfer_guard("disallow"):  # pure host accounting, no device traffic
        ledger = GoodputLedger()
        fractions = ledger.classify(
            {"Time/train_time": 0.8, "Time/env_interaction_time": 0.15}, elapsed_s=1.0
        )
    _assert_sums_to_one(fractions)
    assert math.isclose(fractions["compute"], 0.8)
    assert math.isclose(fractions["env"], 0.15)
    assert math.isclose(fractions["other"], 0.05)
    assert math.isclose(ledger.goodput(), 0.95)


def test_goodput_recompile_storm():
    """A recompile storm (watchdog-drained compile seconds) eats the window."""
    with jax.transfer_guard("disallow"):
        ledger = GoodputLedger()
        fractions = ledger.classify({"Time/train_time": 0.2}, elapsed_s=1.0, recompile_s=0.7)
    _assert_sums_to_one(fractions)
    assert math.isclose(fractions["recompile"], 0.7)
    assert ledger.goodput() < 0.3


def test_goodput_checkpoint_stall():
    with jax.transfer_guard("disallow"):
        ledger = GoodputLedger()
        fractions = ledger.classify(
            {"Time/train_time": 0.3, "Time/phase_checkpoint": 0.6}, elapsed_s=1.0
        )
    _assert_sums_to_one(fractions)
    assert math.isclose(fractions["checkpoint"], 0.6)


def test_goodput_actor_restart_downtime():
    """Supervisor-attributed downtime (actor restart) lands in its own bucket."""
    with jax.transfer_guard("disallow"):
        ledger = GoodputLedger()
        fractions = ledger.classify({"Time/train_time": 0.5}, elapsed_s=2.0, downtime_s=1.0)
    _assert_sums_to_one(fractions)
    assert math.isclose(fractions["downtime"], 0.5)
    assert math.isclose(fractions["compute"], 0.25)


def test_goodput_overlap_clamps_proportionally():
    """Overlapping timers classify more seconds than the wall clock: every
    category scales down so the fractions still sum to exactly 1.0."""
    ledger = GoodputLedger()
    fractions = ledger.classify(
        {"Time/train_time": 1.5, "Time/env_interaction_time": 1.5}, elapsed_s=1.0
    )
    _assert_sums_to_one(fractions)
    assert math.isclose(fractions["compute"], 0.5)
    assert math.isclose(fractions["env"], 0.5)
    assert fractions["other"] == 0.0


def test_goodput_no_double_count_anakin_aliases():
    """Anakin stamps the SAME dispatch block as both Time/phase_dispatch and
    Time/train_time: only the first-present key may count as compute."""
    ledger = GoodputLedger()
    fractions = ledger.classify(
        {"Time/phase_dispatch": 0.6, "Time/train_time": 0.6}, elapsed_s=1.0
    )
    assert math.isclose(fractions["compute"], 0.6), "aliased timers double-counted"
    _assert_sums_to_one(fractions)


def test_goodput_empty_window_is_other():
    ledger = GoodputLedger()
    fractions = ledger.classify({}, elapsed_s=0.0)
    _assert_sums_to_one(fractions)
    assert fractions["other"] == 1.0


def test_goodput_cumulative_fractions():
    ledger = GoodputLedger()
    ledger.classify({"Time/train_time": 1.0}, elapsed_s=1.0)
    ledger.classify({"Time/train_time": 0.0}, elapsed_s=1.0)
    _assert_sums_to_one(ledger.fractions())
    assert math.isclose(ledger.fractions()["compute"], 0.5)
    assert math.isclose(ledger.goodput(), 0.5)


# -------------------------------------------------------- regression watchdog
def test_watchdog_fires_exactly_once_per_sustained_episode():
    dog = StepTimeWatchdog(regress_pct=0.5, warmup_steps=3, sustain_steps=2, alpha=1.0)
    for _ in range(3):
        assert dog.observe(0.01) is None  # warmup builds the baseline
    events = [dog.observe(0.05) for _ in range(6)]  # sustained 5x degradation
    fired = [e for e in events if e is not None]
    assert len(fired) == 1, "one event per sustained episode, no flapping"
    assert fired[0]["capture"] is True
    assert fired[0]["degradation"] > 0.5
    assert dog.anomalies == 1


def test_watchdog_rearms_after_recovery_but_capture_budget_is_spent():
    dog = StepTimeWatchdog(
        regress_pct=0.5, warmup_steps=3, sustain_steps=2, alpha=1.0, max_captures=1
    )
    for _ in range(3):
        dog.observe(0.01)
    first = [dog.observe(0.05) for _ in range(3)]
    assert sum(e is not None for e in first) == 1
    for _ in range(3):
        assert dog.observe(0.01) is None  # recovery re-arms
    second = [dog.observe(0.05) for _ in range(3)]
    fired = [e for e in second if e is not None]
    assert len(fired) == 1, "recovered episode must be able to fire again"
    assert fired[0]["capture"] is False, "capture budget (1) already spent"
    assert dog.anomalies == 2


def test_watchdog_silent_during_warmup_and_transient_blips():
    dog = StepTimeWatchdog(regress_pct=0.5, warmup_steps=3, sustain_steps=3, alpha=1.0)
    assert dog.observe(10.0) is None  # compile-dominated warmup step
    for _ in range(2):
        assert dog.observe(0.01) is None
    # two degraded steps < sustain_steps=3, then recovery: never fires
    assert dog.observe(0.05) is None
    assert dog.observe(0.05) is None
    assert dog.observe(0.01) is None
    assert dog.anomalies == 0


# ------------------------------------------------- cost-model registry + MFU
def test_instrument_registers_cost_model_and_counts_calls():
    """E2E under transfer_guard('disallow'): registration must be a pure
    abstract lowering — no device transfer, no extra sync."""
    cfg = {"obs": {"perf": {"enabled": True}}}

    @jax.jit
    def step(x):
        return jnp.tanh(x @ x)

    wrapped = perf.instrument(cfg, "test/step", step)
    x = jnp.ones((16, 16), jnp.float32)
    wrapped(x)  # first call compiles outside the guard
    with jax.transfer_guard("disallow"):
        out = wrapped(x)
        out = wrapped(out)
    jax.block_until_ready(out)

    models = perf.registered_cost_models()
    assert "test/step" in models
    entry = models["test/step"]
    assert entry["flops"] > 0
    assert entry["calls"] == 3
    # wrapper result identical to the bare fn
    assert jnp.allclose(out, step(step(step(x))))


def test_instrument_disabled_is_identity():
    cfg = {"obs": {"perf": {"enabled": False}}}

    def fn(x):
        return x

    assert perf.instrument(cfg, "test/identity", fn) is fn
    assert perf.registered_cost_models() == {}


def test_register_compiled_from_aot_executable():
    @jax.jit
    def act(x):
        return x @ x

    exe = act.lower(jnp.ones((8, 8), jnp.float32)).compile()
    perf.register_compiled("serve/test/b8", exe)
    models = perf.registered_cost_models()
    assert models["serve/test/b8"]["flops"] > 0
    perf.record_call("serve/test/b8", 5)
    assert perf.registered_cost_models()["serve/test/b8"]["calls"] == 5


def test_mfu_agreement_bench_vs_perf_plane():
    """Satellite (b): bench.py sources FLOPs + peak figures from the perf
    registry helpers — the offline MFU and ``Perf/mfu`` share one definition."""
    spec = importlib.util.spec_from_file_location("bench_under_test", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench.PEAK_FLOPS is perf.PEAK_FLOPS
    assert bench._peak_flops is perf.peak_flops

    device = jax.devices()[0]
    flops, steps_per_sec = 4.2e9, 12.5
    expected = flops * steps_per_sec / perf.peak_flops(device)
    assert math.isclose(perf.mfu_from_flops(flops, steps_per_sec, device), expected)
    assert perf.peak_flops(device) > 0 and perf.peak_hbm_bw(device) > 0


def test_peak_flops_table_device_kinds():
    class _Dev:
        def __init__(self, kind, platform="tpu"):
            self.device_kind = kind
            self.platform = platform

    assert perf.peak_flops(_Dev("TPU v4")) == perf.PEAK_FLOPS["TPU v4"]
    assert perf.peak_flops(_Dev("TPU v5 lite")) == perf.PEAK_FLOPS["TPU v5 lite"]
    # unknown accelerator falls to the v4 default; CPUs get the nominal figure
    assert perf.peak_flops(_Dev("TPU v9")) == 275e12
    assert 0 < perf.peak_flops(_Dev("cpu", platform="cpu")) < 1e12


# ------------------------------------------------------------ PerfPlane flush
def test_perf_plane_flush_emits_gauges_and_report(tmp_path):
    cfg = {"obs": {"perf": {"enabled": True}}}

    @jax.jit
    def step(x):
        return x @ x

    wrapped = perf.instrument(cfg, "plane/step", step)
    plane = PerfPlane(cfg)
    x = jnp.ones((32, 32), jnp.float32)
    jax.block_until_ready(wrapped(x))
    time.sleep(0.01)
    metrics = {"Time/train_time": 0.01}
    plane.flush(metrics)
    assert metrics["Perf/achieved_flops_per_sec"] > 0
    assert metrics["Perf/mfu"] > 0
    assert "Perf/goodput" in metrics and "Perf/anomalies" in metrics
    _assert_sums_to_one({c: metrics[f"Perf/goodput_{c}"] for c in GOODPUT_CATEGORIES})

    path = str(tmp_path / "perf_report.json")
    assert plane.write_report(path) == path
    report = json.load(open(path))
    assert report["mfu"] > 0
    assert report["total_flops"] > 0
    assert "plane/step" in report["cost_models"]
    _assert_sums_to_one(report["goodput_fractions"])


def test_perf_plane_disabled_is_inert(tmp_path):
    plane = PerfPlane({"obs": {"perf": {"enabled": False}}})
    assert plane.observe_step() is None
    metrics = {}
    plane.flush(metrics)
    assert metrics == {}
    assert plane.write_report(str(tmp_path / "nope.json")) is None
    assert not (tmp_path / "nope.json").exists()


# ----------------------------------------------------------------- monitor e2e
def test_monitor_forced_slowdown_one_capture_and_report(tmp_path):
    """The acceptance scenario: a post-warmup slowdown sustained past
    ``sustain_steps`` fires EXACTLY ONE auto-capture and one ``perf_regression``
    flight-recorder event; close() writes perf_report.json with nonzero MFU and
    goodput fractions summing to 1.0."""
    cfg = {
        "algo": {"name": "test"},
        "obs": {
            "enabled": False,
            "flight_recorder": False,
            "perf": {
                "enabled": True,
                "regress_pct": 0.5,
                "warmup_steps": 3,
                "sustain_steps": 2,
                "ewma_alpha": 1.0,
                "max_captures": 1,
                "capture_updates": 2,
            },
        },
    }
    recorder = flight_recorder_mod.FlightRecorder(str(tmp_path))
    flight_recorder_mod.install(recorder)
    monitor = TrainingMonitor(cfg, log_dir=str(tmp_path))
    starts, stops = [], []
    monitor._start_capture = lambda: (starts.append(1), setattr(monitor, "_capturing", True))
    monitor._stop_capture = lambda: (stops.append(1), setattr(monitor, "_capturing", False))

    @jax.jit
    def step(x):
        return x @ x

    wrapped = perf.instrument(cfg, "monitor/step", step)
    x = jnp.ones((16, 16), jnp.float32)
    for _ in range(4):  # warmup: fast steps establish the baseline
        jax.block_until_ready(wrapped(x))
        monitor.advance()
        time.sleep(0.002)
    for _ in range(6):  # sustained ~25x degradation
        jax.block_until_ready(wrapped(x))
        monitor.advance()
        time.sleep(0.05)

    assert len(starts) == 1, "exactly one auto-capture per run"
    assert len(stops) == 1, "capture window must close after capture_updates"
    events = [e for e in recorder.events() if e.get("kind") == "perf_regression"]
    assert len(events) == 1
    assert events[0]["capture"] is True
    assert events[0]["degradation"] > 0.5

    metrics = {"Time/train_time": 0.3}
    monitor.log_metrics(None, metrics, step=1)
    assert "Perf/goodput" in metrics

    monitor.close()
    report_file = tmp_path / "perf_report.json"
    assert report_file.exists()
    report = json.load(open(report_file))
    assert report["mfu"] > 0
    assert report["anomalies"] == 1
    assert len(report["anomaly_events"]) == 1
    _assert_sums_to_one(report["goodput_fractions"])
    assert report["cost_models"]["monitor/step"]["calls"] == 10
