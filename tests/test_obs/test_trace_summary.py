"""benchmarks/trace_summary.py folds a tracer export into a per-phase time table."""

import importlib.util
import pathlib
import time

import pytest

from sheeprl_tpu.obs import tracer as tr
from sheeprl_tpu.obs.tracer import SpanTracer, span

_SPEC = importlib.util.spec_from_file_location(
    "trace_summary", pathlib.Path(__file__).resolve().parents[2] / "benchmarks" / "trace_summary.py"
)
trace_summary = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(trace_summary)


@pytest.fixture()
def trace_file(tmp_path):
    t = SpanTracer(rank=0)
    prev = tr.set_active(t)
    try:
        for _ in range(3):
            with span("Time/update"):
                with span("Time/train_time"):
                    time.sleep(0.001)
                with span("Time/env_interaction_time"):
                    pass
    finally:
        tr.set_active(prev)
    path = tmp_path / "trace.json"
    t.export_chrome_trace(str(path))
    return path


def test_summarize_per_phase(trace_file):
    summary = trace_summary.summarize(str(trace_file))
    phases = summary["phases"]
    assert set(phases) == {"Time/update", "Time/train_time", "Time/env_interaction_time"}
    assert phases["Time/train_time"]["count"] == 3
    # updates are the only depth-0 spans: their total IS the top-level wall clock
    assert summary["top_level_total_ms"] == pytest.approx(phases["Time/update"]["total_ms"])
    assert phases["Time/update"]["share"] == pytest.approx(1.0)
    # nested phases can't exceed their parent's share
    assert phases["Time/train_time"]["share"] < 1.0
    assert phases["Time/train_time"]["p50_ms"] <= phases["Time/train_time"]["p99_ms"]


def test_format_table_and_cli(trace_file, capsys):
    assert trace_summary.main([str(trace_file)]) == 0
    out = capsys.readouterr().out
    assert "Time/train_time" in out and "share" in out and "top-level wall clock" in out
    assert trace_summary.main([str(trace_file), "--json"]) == 0
    assert '"phases"' in capsys.readouterr().out


# ------------------------------------------------------- blackbox event folding
@pytest.fixture()
def blackbox_log(tmp_path):
    import json

    from sheeprl_tpu.obs.flight_recorder import FlightRecorder

    r = FlightRecorder(str(tmp_path), keep_events=64)
    for i in range(3):
        r.record("span", name="Time/update", dur_ms=10.0 + i, depth=0)
        r.record("span", name="Time/phase_dispatch", dur_ms=4.0, depth=1)
        r.record("metric_flush", step=i, n_metrics=5)
    r.record("rollout_restart", worker=0, reason="timeout")
    r.record("nonfinite", labels=["x"])
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        for event in r.events():
            f.write(json.dumps(event) + "\n")
    return path


def test_summarize_blackbox_events(blackbox_log):
    summary = trace_summary.summarize(str(blackbox_log))
    assert set(summary["phases"]) == {"Time/update", "Time/phase_dispatch"}
    assert summary["phases"]["Time/update"]["count"] == 3
    assert summary["top_level_total_ms"] == pytest.approx(33.0)
    assert summary["events"] == {"metric_flush": 3, "rollout_restart": 1, "nonfinite": 1}


def test_blackbox_table_includes_event_section(blackbox_log):
    summary = trace_summary.summarize(str(blackbox_log))
    table = trace_summary.format_table(summary)
    assert "flight-recorder events:" in table
    assert "rollout_restart: 1" in table


def test_chrome_trace_path_still_detected(trace_file):
    # The sniffing must not misroute ordinary Chrome traces.
    summary = trace_summary.summarize(str(trace_file))
    assert "events" not in summary
