"""benchmarks/trace_summary.py folds a tracer export into a per-phase time table."""

import importlib.util
import pathlib
import time

import pytest

from sheeprl_tpu.obs import tracer as tr
from sheeprl_tpu.obs.tracer import SpanTracer, span

_SPEC = importlib.util.spec_from_file_location(
    "trace_summary", pathlib.Path(__file__).resolve().parents[2] / "benchmarks" / "trace_summary.py"
)
trace_summary = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(trace_summary)


@pytest.fixture()
def trace_file(tmp_path):
    t = SpanTracer(rank=0)
    prev = tr.set_active(t)
    try:
        for _ in range(3):
            with span("Time/update"):
                with span("Time/train_time"):
                    time.sleep(0.001)
                with span("Time/env_interaction_time"):
                    pass
    finally:
        tr.set_active(prev)
    path = tmp_path / "trace.json"
    t.export_chrome_trace(str(path))
    return path


def test_summarize_per_phase(trace_file):
    summary = trace_summary.summarize(str(trace_file))
    phases = summary["phases"]
    assert set(phases) == {"Time/update", "Time/train_time", "Time/env_interaction_time"}
    assert phases["Time/train_time"]["count"] == 3
    # updates are the only depth-0 spans: their total IS the top-level wall clock
    assert summary["top_level_total_ms"] == pytest.approx(phases["Time/update"]["total_ms"])
    assert phases["Time/update"]["share"] == pytest.approx(1.0)
    # nested phases can't exceed their parent's share
    assert phases["Time/train_time"]["share"] < 1.0
    assert phases["Time/train_time"]["p50_ms"] <= phases["Time/train_time"]["p99_ms"]


def test_format_table_and_cli(trace_file, capsys):
    assert trace_summary.main([str(trace_file)]) == 0
    out = capsys.readouterr().out
    assert "Time/train_time" in out and "share" in out and "top-level wall clock" in out
    assert trace_summary.main([str(trace_file), "--json"]) == 0
    assert '"phases"' in capsys.readouterr().out
