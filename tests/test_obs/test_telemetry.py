"""Device telemetry: graceful on CPU (memory_stats() is None), full keys on fakes."""

from sheeprl_tpu.obs.telemetry import DeviceTelemetry


class _FakeDevice:
    def __init__(self, in_use, peak, limit=1 << 30):
        self._stats = {"bytes_in_use": in_use, "peak_bytes_in_use": peak, "bytes_limit": limit}

    def memory_stats(self):
        return self._stats


class _StatlessDevice:
    def memory_stats(self):
        return None


def test_cpu_backend_poll_is_graceful():
    # Real CPU devices return None from memory_stats(): no Memory/*/devN keys, but the
    # host-RSS fallback still gives a Memory/* signal.
    t = DeviceTelemetry(interval_s=0.0)
    out = t.poll(force=True)
    assert not any(k.startswith("Memory/bytes_in_use/") for k in out)
    assert out.get("Memory/host_peak_rss_bytes", 0) > 0


def test_fake_device_stats_and_aggregates():
    t = DeviceTelemetry(interval_s=0.0, devices=[_FakeDevice(100, 150), _FakeDevice(200, 300)])
    out = t.poll(force=True)
    assert out["Memory/bytes_in_use/dev0"] == 100.0
    assert out["Memory/peak_bytes_in_use/dev1"] == 300.0
    assert out["Memory/bytes_limit/dev0"] == float(1 << 30)
    assert out["Memory/bytes_in_use"] == 300.0  # sum across devices
    assert out["Memory/peak_bytes_in_use"] == 300.0  # max across devices


def test_mixed_devices_skip_statless():
    t = DeviceTelemetry(interval_s=0.0, devices=[_StatlessDevice(), _FakeDevice(50, 60)])
    out = t.poll(force=True)
    assert "Memory/bytes_in_use/dev0" not in out
    assert out["Memory/bytes_in_use/dev1"] == 50.0


def test_interval_gating():
    t = DeviceTelemetry(interval_s=3600.0, devices=[_FakeDevice(1, 2)])
    assert t.poll()  # first poll always fires (last_poll = -inf)
    assert t.poll() == {}  # gated
    assert t.poll(force=True)  # force bypasses the gate
    assert t.last["Memory/bytes_in_use/dev0"] == 1.0
