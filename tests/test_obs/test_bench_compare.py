"""benchmarks/bench_compare.py: BENCH report diffing + regression flags."""

import importlib.util
import json
import pathlib

_SPEC = importlib.util.spec_from_file_location(
    "bench_compare", pathlib.Path(__file__).resolve().parents[2] / "benchmarks" / "bench_compare.py"
)
bench_compare = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench_compare)


def _report(tmp_path, name, metrics):
    tail = "\n".join(json.dumps({"metric": m, "value": v, "unit": u}) for m, (v, u) in metrics.items())
    path = tmp_path / name
    path.write_text(json.dumps({"n": 1, "rc": 0, "tail": "noise line\n" + tail}))
    return str(path)


def test_extracts_metric_rows_from_tail(tmp_path):
    path = _report(tmp_path, "BENCH_a.json", {"sps": (100.0, "grad_steps/s")})
    assert bench_compare.extract_metrics(path) == {"sps": (100.0, "grad_steps/s")}


def test_flags_throughput_drop_beyond_threshold(tmp_path):
    base = _report(tmp_path, "BENCH_a.json", {"sps": (100.0, "grad_steps/s"), "lat": (10.0, "ms")})
    new = _report(tmp_path, "BENCH_b.json", {"sps": (85.0, "grad_steps/s"), "lat": (10.5, "ms")})
    report = bench_compare.compare(base, new, threshold=0.10)
    assert report["regressions"] == ["sps"]  # -15% throughput; +5% latency is fine


def test_latency_metrics_regress_upward(tmp_path):
    base = _report(tmp_path, "BENCH_a.json", {"step_time_ms": (10.0, "ms")})
    new = _report(tmp_path, "BENCH_b.json", {"step_time_ms": (12.0, "ms")})
    report = bench_compare.compare(base, new, threshold=0.10)
    assert report["regressions"] == ["step_time_ms"]


def test_within_threshold_is_clean_and_cli_exit_codes(tmp_path, capsys):
    base = _report(tmp_path, "BENCH_a.json", {"sps": (100.0, "grad_steps/s")})
    new = _report(tmp_path, "BENCH_b.json", {"sps": (95.0, "grad_steps/s")})
    assert bench_compare.main([base, new]) == 0
    assert "no regressions" in capsys.readouterr().out

    bad = _report(tmp_path, "BENCH_c.json", {"sps": (50.0, "grad_steps/s")})
    assert bench_compare.main([base, bad]) == 0  # non-strict: warn only
    assert bench_compare.main([base, bad, "--strict"]) == 1


def test_disjoint_metric_sets_reported(tmp_path):
    base = _report(tmp_path, "BENCH_a.json", {"old_metric": (1.0, "")})
    new = _report(tmp_path, "BENCH_b.json", {"new_metric": (1.0, "")})
    report = bench_compare.compare(base, new)
    assert report["only_in_base"] == ["old_metric"]
    assert report["only_in_new"] == ["new_metric"]
    assert report["rows"] == [] and report["regressions"] == []


def test_dropped_metric_warns_loudly_and_fails_strict(tmp_path, capsys):
    """A metric present in the baseline but absent from the latest report used to
    read as a silent pass — it must be listed loudly and fail --strict."""
    base = _report(tmp_path, "BENCH_a.json", {"sps": (100.0, "grad_steps/s"), "gone": (5.0, "x/s")})
    new = _report(tmp_path, "BENCH_b.json", {"sps": (101.0, "grad_steps/s")})

    report = bench_compare.compare(base, new, threshold=0.10)
    assert report["dropped_metrics"] == ["gone"]
    assert report["regressions"] == []

    table = bench_compare.format_table(report)
    assert "WARNING" in table and "DROPPED: gone" in table

    rc = bench_compare.main([base, new, "--strict"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "dropped metric(s): gone" in captured.err

    # non-strict: loud but non-fatal (CI's continue-on-error contract)
    assert bench_compare.main([base, new]) == 0


def test_serve_direction_pins_exact_name_beats_prefix(tmp_path):
    """serve_* rows are throughput (higher-better by prefix) EXCEPT the exact-name
    latency/startup pins: serve_p99_ms and serve_startup_seconds regress UPWARD
    even though their prefix says higher-better."""
    assert bench_compare.lower_is_better("serve_throughput_rps", "replies/s") is False
    # the unit string mentions "ms"/"seconds", but the prefix pin wins over hints…
    assert bench_compare.lower_is_better("serve_whatever_new_row", "ms of something") is False
    # …and the exact-name pins win over the prefix.
    assert bench_compare.lower_is_better("serve_p99_ms", "ms enqueue->reply p99") is True
    assert bench_compare.lower_is_better("serve_startup_seconds", "s spawn->ready") is True

    base = _report(
        tmp_path,
        "BENCH_a.json",
        {"serve_throughput_rps": (1000.0, "replies/s"), "serve_p99_ms": (5.0, "ms")},
    )
    new = _report(
        tmp_path,
        "BENCH_b.json",
        {"serve_throughput_rps": (500.0, "replies/s"), "serve_p99_ms": (10.0, "ms")},
    )
    report = bench_compare.compare(base, new, threshold=0.10)
    assert report["regressions"] == ["serve_p99_ms", "serve_throughput_rps"]


def test_precision_rows_direction_pins(tmp_path):
    """precision_* rows (benchmarks/precision_bench.py) are higher-better by
    prefix pin — an agreement fraction that DROPS is the regression — and the
    bf16/int8 throughput rows ride the existing anakin_/serve_ prefixes.
    Precedence stays: exact-name pins > prefix pins > unit-text hints."""
    assert bench_compare.lower_is_better("precision_parity_action_agreement", "fraction") is False
    # "time"-ish unit text must NOT flip a precision_* row to lower-better
    assert bench_compare.lower_is_better("precision_parity_kl", "nats at eval time") is False
    assert bench_compare.lower_is_better("anakin_bf16_steps_per_sec", "env_steps/s") is False
    assert bench_compare.lower_is_better("serve_int8_replies_per_sec", "replies/s") is False
    # exact-name latency pins still beat every prefix
    assert bench_compare.lower_is_better("serve_p99_ms", "ms") is True

    base = _report(
        tmp_path,
        "BENCH_a.json",
        {"precision_parity_action_agreement": (1.0, "fraction"), "serve_int8_replies_per_sec": (900.0, "replies/s")},
    )
    new = _report(
        tmp_path,
        "BENCH_b.json",
        {"precision_parity_action_agreement": (0.80, "fraction"), "serve_int8_replies_per_sec": (950.0, "replies/s")},
    )
    report = bench_compare.compare(base, new, threshold=0.10)
    assert report["regressions"] == ["precision_parity_action_agreement"]


def test_no_dropped_metrics_strict_stays_green(tmp_path):
    base = _report(tmp_path, "BENCH_a.json", {"sps": (100.0, "grad_steps/s")})
    new = _report(tmp_path, "BENCH_b.json", {"sps": (102.0, "grad_steps/s"), "extra": (1.0, "x")})
    assert bench_compare.main([base, new, "--strict"]) == 0


def test_race_detect_overhead_direction_pin_and_row(tmp_path):
    """race_detect_overhead_pct (benchmarks/race_detect_bench.py) is an overhead
    percentage: it regresses when it RISES, pinned lower-better by exact name
    (no prefix pin covers race_*; the unit text alone would not flip it)."""
    assert bench_compare.lower_is_better("race_detect_overhead_pct", "% wall-time overhead") is True

    base = _report(tmp_path, "BENCH_a.json", {"race_detect_overhead_pct": (5.0, "%")})
    new = _report(tmp_path, "BENCH_b.json", {"race_detect_overhead_pct": (12.0, "%")})
    report = bench_compare.compare(base, new, threshold=0.10)
    assert report["regressions"] == ["race_detect_overhead_pct"]
    # improvement direction: dropping overhead is NOT a regression
    report = bench_compare.compare(new, base, threshold=0.10)
    assert report["regressions"] == []


def test_race_detect_bench_row_shape():
    """A tiny in-process run of the bench: the row carries the pinned metric
    name, a non-negative value, and the detector's bookkeeping counters — and
    the workload itself is cycle-free (consistent lock order)."""
    spec = importlib.util.spec_from_file_location(
        "race_detect_bench",
        pathlib.Path(__file__).resolve().parents[2] / "benchmarks" / "race_detect_bench.py",
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    row = bench.run_bench(items=400, n_threads=2, repeats=1, work_us=10.0)
    assert row["metric"] == "race_detect_overhead_pct"
    assert row["value"] >= 0.0
    assert row["acquisitions"] > 0
    assert row["cycles"] == 0
