"""Fleet telemetry plane (ISSUE 16): aggregator merge semantics, tag schema,
snapshot liveness + dead-exporter eviction, blackbox bundles, the `top` view,
hot-path hygiene (zero host syncs, ≤2% step overhead), and — slow tier — a real
2-actor launcher run producing one merged timeline + one Perfetto file."""

import importlib.util
import json
import os
import pathlib
import socket
import subprocess
import sys
import time

import pytest

from sheeprl_tpu.distributed.transport import connect
from sheeprl_tpu.obs import flight_recorder as flight_recorder_mod
from sheeprl_tpu.obs import top as fleet_top
from sheeprl_tpu.obs.fleet import (
    FLEET_ENV_VAR,
    ROW_TAG_KEYS,
    TRACE_ID_ENV_VAR,
    FleetAggregator,
    FleetExporter,
    maybe_exporter,
    merge_chrome_traces,
)

REPO = pathlib.Path(__file__).resolve().parents[2]


def _load_bench_module(name):
    spec = importlib.util.spec_from_file_location(name, REPO / "benchmarks" / f"{name}.py")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _wait_for(predicate, timeout_s=5.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _timeline_rows(agg):
    rows = []
    try:
        with open(agg.timeline_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    except OSError:
        pass
    return rows


def _exporter(agg, role, actor_id=0, generation=0, interval_s=60.0, log_dir=None):
    """A client exporter wired to ``agg`` with a long interval: tests drive
    flushes explicitly so assertions never race the heartbeat."""
    host, port = agg.address.rsplit(":", 1)
    tags = {
        "role": role,
        "actor_id": actor_id,
        "generation": generation,
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "trace_id": agg.trace_id,
    }
    ch = connect(host, int(port), timeout_s=5.0)
    return FleetExporter(tags, channel=ch, interval_s=interval_s, log_dir=log_dir)


@pytest.fixture(autouse=True)
def _clean_fleet_env(monkeypatch):
    monkeypatch.delenv(FLEET_ENV_VAR, raising=False)
    monkeypatch.delenv(TRACE_ID_ENV_VAR, raising=False)


# --------------------------------------------------------------- merge + tags
def test_timeline_rows_carry_full_tag_schema_and_rates(tmp_path):
    """Every timeline row is stamped with the pinned tag schema, rows from
    several processes merge into ONE file, and cumulative counters are folded
    into ``<name>_per_s`` rates between consecutive rows of the same slot."""
    agg = FleetAggregator(str(tmp_path / "fleet"), trace_id="tid-test")
    try:
        learner = _exporter(agg, "learner")
        actor = _exporter(agg, "actor", actor_id=1)
        learner.counter("grad_steps", 0)
        assert learner.flush()
        actor.counter("env_steps", 100)
        assert actor.flush()
        time.sleep(0.25)
        learner.counter("grad_steps", 50)
        learner.gauge("Sebulba/queue_depth", 2)
        assert learner.flush()
        _wait_for(lambda: agg.rows_written >= 3, msg="3 timeline rows")
        learner.close()
        actor.close()

        rows = _timeline_rows(agg)
        assert len(rows) >= 3
        for row in rows:
            assert set(ROW_TAG_KEYS) <= set(row), f"row missing tags: {sorted(row)}"
            assert row["trace_id"] == "tid-test"
            assert isinstance(row["metrics"], dict)
        roles = {(r["role"], r["actor_id"]) for r in rows}
        assert ("learner", 0) in roles and ("actor", 1) in roles

        learner_rows = [r for r in rows if r["role"] == "learner"]
        rated = [r for r in learner_rows if "grad_steps_per_s" in r["metrics"]]
        assert rated, "no derived grad_steps_per_s rate on any learner row"
        # 50 grad steps over ~0.25s: the rate is large and positive, never the
        # raw cumulative value.
        assert rated[0]["metrics"]["grad_steps_per_s"] > 0
        assert any(r["metrics"].get("Sebulba/queue_depth") == 2 for r in learner_rows)
        # seq increases monotonically per process
        seqs = [r["seq"] for r in learner_rows]
        assert seqs == sorted(seqs)
    finally:
        agg.close()


def test_respawned_actor_replaces_its_slot_row(tmp_path):
    """Slot semantics: a respawned actor (same actor_id, new generation) takes
    over its predecessor's snapshot row; respawn counts ride the snapshot via
    the launcher's ``note_respawn`` hook."""
    agg = FleetAggregator(str(tmp_path / "fleet"))
    try:
        gen0 = _exporter(agg, "actor", actor_id=0, generation=0)
        assert gen0.flush()
        _wait_for(lambda: "actor0" in agg.snapshot()["processes"], msg="gen0 registered")
        gen0.close()

        agg.note_respawn(0, 1)
        gen1 = _exporter(agg, "actor", actor_id=0, generation=1)
        assert gen1.flush()
        _wait_for(
            lambda: agg.snapshot()["processes"].get("actor0", {}).get("generation") == 1,
            msg="gen1 took over the slot",
        )
        snap = agg.snapshot()
        assert list(snap["processes"]) == ["actor0"], "respawn must replace, not duplicate"
        row = snap["processes"]["actor0"]
        assert row["alive"] is True
        assert row["respawns"] == 1
        gen1.close()
    finally:
        agg.close()


def test_snapshot_liveness_and_dead_exporter_eviction(tmp_path):
    """A clean BYE keeps the row (done=True); an abrupt channel death keeps the
    row only until ``liveness_timeout_s`` — then it is evicted."""
    agg = FleetAggregator(str(tmp_path / "fleet"), liveness_timeout_s=1.0)
    try:
        clean = _exporter(agg, "learner")
        dead = _exporter(agg, "actor", actor_id=1)
        assert clean.flush() and dead.flush()
        _wait_for(lambda: len(agg.snapshot()["processes"]) == 2, msg="both registered")

        clean.close()  # BYE -> done
        assert dead.flush()  # refresh the liveness clock: the eviction window starts NOW
        dead._ch.close()  # simulated crash: no BYE
        # the death notice and the BYE ride two separate reader threads — wait
        # for both inside the eviction window before asserting the snapshot.
        def _settled():
            procs = agg.snapshot()["processes"]
            return (
                not procs.get("actor1", {}).get("alive", True)
                and procs.get("learner0", {}).get("done") is True
            )

        _wait_for(_settled, msg="dead channel noticed and BYE processed")
        snap = agg.snapshot()
        assert snap["processes"]["learner0"]["done"] is True
        assert "actor1" in snap["processes"], "dead slot evicted before the timeout"

        time.sleep(1.1)
        snap = agg.snapshot()
        assert "actor1" not in snap["processes"], "dead+silent slot not evicted"
        assert "learner0" in snap["processes"], "clean-done slot must survive eviction"
        dead.close()
    finally:
        agg.close()


def test_merge_chrome_traces_rewrites_pids():
    """Per-process tracers all say rank-0 pid; the merge maps each stream to its
    real OS pid with a role-labeled process_name — one Perfetto doc, N tracks."""
    ev = {"name": "Time/update", "ph": "X", "pid": 0, "tid": 0, "ts": 1, "dur": 5}
    merged = merge_chrome_traces(
        [
            ({"role": "learner", "actor_id": 0, "pid": 111}, [dict(ev)]),
            ({"role": "actor", "actor_id": 1, "pid": 222}, [dict(ev)]),
        ]
    )
    events = merged["traceEvents"]
    assert {e["pid"] for e in events if e.get("ph") == "X"} == {111, 222}
    names = {e["pid"]: e["args"]["name"] for e in events if e.get("name") == "process_name"}
    assert names[111] == "learner (pid 111)"
    assert names[222] == "actor1 (pid 222)"


# ----------------------------------------------------------------- blackboxes
def test_fleet_blackbox_bundle(tmp_path):
    """collect_blackboxes gathers every survivor's flight-recorder ring inline,
    copies on-disk blackbox dumps from remembered log dirs, writes a manifest,
    and caps the number of bundles."""
    log_dir = tmp_path / "actor_logs"
    (log_dir / "blackbox").mkdir(parents=True)
    (log_dir / "blackbox" / "events.jsonl").write_text('{"kind": "span"}\n')

    recorder = flight_recorder_mod.FlightRecorder(
        log_dir=str(tmp_path / "rec"), capacity=64, keep_events=32, algo="test", cfg={}
    )
    flight_recorder_mod.install(recorder)
    try:
        flight_recorder_mod.record_event("metric_flush", step=7)
        agg = FleetAggregator(str(tmp_path / "run" / "fleet"))
        try:
            exp = _exporter(agg, "learner", log_dir=str(log_dir))
            assert exp.flush()
            _wait_for(lambda: agg.rows_written >= 1, msg="row ingested")

            bundle = agg.collect_blackboxes("actor1_rc9")
            assert bundle is not None
            bundle = pathlib.Path(bundle)
            assert bundle.parent.name == "blackbox_fleet"
            manifest = json.loads((bundle / "manifest.json").read_text())
            assert manifest["reason"] == "actor1_rc9"
            assert manifest["trace_id"] == agg.trace_id
            assert manifest["peers"], "no surviving peer replied with its ring"
            peer_dir = bundle / manifest["peers"][0]["slot"]
            events = [
                json.loads(line)
                for line in (peer_dir / "events.jsonl").read_text().splitlines()
            ]
            assert any(e.get("kind") == "metric_flush" for e in events)
            # the dead child's on-disk dump came along via the hello's log_dir
            disk_copies = list(bundle.glob("*_disk"))
            assert disk_copies and (disk_copies[0] / "events.jsonl").is_file()
            # the ring is a copy, not a consumed one-shot: dump_active still works
            assert flight_recorder_mod.get_active() is recorder

            assert agg.collect_blackboxes("two") is not None
            assert agg.collect_blackboxes("three") is not None
            assert agg.collect_blackboxes("four") is None, "bundle cap not enforced"
            exp.close()
        finally:
            agg.close()
    finally:
        flight_recorder_mod.install(None)


# -------------------------------------------------------------- maybe_exporter
def test_maybe_exporter_disabled_and_unconfigured(tmp_path):
    assert maybe_exporter({"obs": {"fleet": {"enabled": False, "dir": str(tmp_path)}}}, "learner") is None
    assert maybe_exporter({"obs": {"fleet": {"enabled": True}}}, "learner") is None
    assert maybe_exporter({}, "learner") is None


def test_maybe_exporter_local_dir_mode(tmp_path):
    """No launcher address, but ``obs.fleet.dir`` set: the process hosts a
    private in-process aggregator and exports to it over localhost — the same
    files, the same code path (standalone serve replicas, tests)."""
    fleet_dir = tmp_path / "fleet"
    cfg = {"obs": {"fleet": {"enabled": True, "dir": str(fleet_dir), "interval_s": 60.0}}}
    exporter = maybe_exporter(cfg, "serve", generation=2)
    assert exporter is not None
    try:
        exporter.counter("requests_replied", 10)
        assert exporter.flush()
        _wait_for(lambda: (fleet_dir / "timeline.jsonl").exists(), msg="timeline created")
        _wait_for(
            lambda: any(
                r.get("role") == "serve"
                for r in (
                    json.loads(line)
                    for line in (fleet_dir / "timeline.jsonl").read_text().splitlines()
                    if line.strip()
                )
            ),
            msg="serve row written",
        )
    finally:
        exporter.close()
    rows = [
        json.loads(line)
        for line in (fleet_dir / "timeline.jsonl").read_text().splitlines()
        if line.strip()
    ]
    assert rows and rows[0]["role"] == "serve" and rows[0]["generation"] == 2
    assert (fleet_dir / "snapshot.json").exists()


# ------------------------------------------------------------------ hot path
def test_exporter_hot_path_no_host_sync(tmp_path):
    """The per-step API (counter/gauge) must not force a device→host sync: a
    jitted step keeps executing under ``transfer_guard("disallow")`` while the
    loop records telemetry (PR-4 health-diagnostics pattern)."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    agg = FleetAggregator(str(tmp_path / "fleet"))
    exporter = _exporter(agg, "learner")
    try:
        step = jax.jit(lambda x: x * 1.0001 + 0.1)
        x = jax.device_put(jnp.ones((32, 32), jnp.float32))
        x = step(x)
        jax.block_until_ready(x)  # compile outside the guard
        with jax.transfer_guard("disallow"):
            for i in range(20):
                x = step(x)
                exporter.counter("grad_steps", i)
                exporter.counter("env_steps", i * 64)
                exporter.gauge("Sebulba/queue_depth", i % 3)
        jax.block_until_ready(x)
        assert exporter.flush()
        _wait_for(lambda: agg.rows_written >= 1, msg="row after guarded loop")
    finally:
        exporter.close()
        agg.close()


def test_export_overhead_under_two_percent():
    """Acceptance: the telemetry plane costs ≤2% of step time against a LIVE
    loopback aggregator (same bench that emits ``obs_fleet_overhead_pct``)."""
    bench = _load_bench_module("obs_overhead_bench")
    rows = [bench.run_bench(steps=200, step_ms=2.0, repeats=2) for _ in range(3)]
    best = min(r["value"] for r in rows)
    assert best <= 2.0, f"fleet export overhead {best:.2f}% > 2% (rows: {rows})"


# -------------------------------------------------------- learner summary path
def test_learner_summary_written_on_exception(tmp_path, monkeypatch):
    """A learner that dies before (or inside) its loop still leaves a summary
    JSON with the failure — previously only the happy path wrote it."""
    from sheeprl_tpu.distributed import sebulba
    from sheeprl_tpu.distributed.placement import SUMMARY_ENV_VAR, PlacementSpec

    summary_path = tmp_path / "summary.json"
    monkeypatch.setenv(SUMMARY_ENV_VAR, str(summary_path))
    monkeypatch.setattr(sebulba, "_summary_written", False)

    def _boom(ctx, cfg, spec):
        raise RuntimeError("learner setup exploded")

    monkeypatch.setitem(sebulba._RUNNERS, ("sac", "learner"), _boom)
    spec = PlacementSpec(mode="sebulba", role="learner")
    with pytest.raises(RuntimeError, match="exploded"):
        sebulba.run(None, {}, spec, "sac")
    summary = json.loads(summary_path.read_text())
    assert summary["error"]["type"] == "RuntimeError"
    assert "exploded" in summary["error"]["message"]
    assert summary["blocks"] == 0 and summary["cumulative_grad_steps"] == 0


# ------------------------------------------------------------------- top view
def test_top_once_renders_snapshot(tmp_path, capsys):
    agg = FleetAggregator(str(tmp_path / "fleet"))
    try:
        learner = _exporter(agg, "learner")
        learner.counter("grad_steps", 0)
        learner.flush()
        time.sleep(0.15)
        learner.counter("grad_steps", 30)
        learner.gauge("Sebulba/queue_depth", 4)
        learner.flush()
        _wait_for(lambda: agg.rows_written >= 2, msg="rows for top")
        learner.close()
    finally:
        agg.close()

    rc = fleet_top.main([str(tmp_path / "fleet"), "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "learner0" in out and "GRAD/S" in out and "QDEPTH" in out

    empty = tmp_path / "nothing"
    empty.mkdir()
    assert fleet_top.main([str(empty), "--once"]) == 2


def test_top_rebuilds_from_timeline_tail(tmp_path):
    """snapshot.json missing (aggregator died pre-write): top falls back to the
    timeline tail and marks every row not-alive."""
    fleet_dir = tmp_path / "fleet"
    fleet_dir.mkdir()
    row = {k: None for k in ROW_TAG_KEYS}
    row.update(role="actor", actor_id=1, generation=0, pid=42, wall_clock=time.time(), seq=3)
    row["metrics"] = {"env_steps_per_s": 12.5}
    (fleet_dir / "timeline.jsonl").write_text(json.dumps(row) + "\n")
    snap = fleet_top.load_snapshot(str(fleet_dir))
    assert snap is not None and snap.get("rebuilt_from_timeline")
    assert snap["processes"]["actor1"]["alive"] is False
    table = fleet_top.format_top(snap)
    assert "actor1" in table and "12.5" in table


def test_top_renders_fleet_front_detail_line():
    """A front slot gets the router detail line: per-replica routed share,
    reroute count, admit/retire tallies and canary agreement."""
    snap = {
        "fleet_dir": "/tmp/f",
        "processes": {
            "front0": {
                "role": "front",
                "generation": 0,
                "pid": 7,
                "alive": True,
                "wall_clock": time.time(),
                "metrics": {
                    "Fleet/pending": 3,
                    "Fleet/latency_p99_ms": 8.5,
                    "Fleet/reroutes": 2,
                    "Fleet/replicas_admitted": 3,
                    "Fleet/replicas_retired": 1,
                    "Fleet/live_replicas": 2,
                    "Fleet/canary_agreement": 0.995,
                    "Fleet/share/replica0": 0.75,
                    "Fleet/share/replica1": 0.25,
                },
            },
        },
    }
    table = fleet_top.format_top(snap)
    assert "front0" in table and "8.5" in table  # QDEPTH/P99 via Fleet/ gauges
    detail = next(line for line in table.splitlines() if line.startswith("front front0:"))
    assert "replica0=75%" in detail and "replica1=25%" in detail
    assert "reroutes=2" in detail and "replicas +3/-1" in detail
    assert "live=2" in detail and "canary_agreement=0.995" in detail


# ----------------------------------------------------------- trace_summary tie
def test_trace_summary_folds_fleet_timeline(tmp_path):
    trace_summary = _load_bench_module("trace_summary")
    timeline = tmp_path / "timeline.jsonl"
    rows = []
    for i, wall in enumerate((100.0, 101.0)):
        rows.append(
            {
                "role": "learner",
                "actor_id": 0,
                "generation": 0,
                "host": "h",
                "pid": 7,
                "wall_clock": wall,
                "trace_id": "tid",
                "seq": i + 1,
                "metrics": {
                    "grad_steps_per_s": 40.0 * i,
                    "Sebulba/publish_apply_ms": 3.0 + i,
                },
            }
        )
    rows.append(
        {
            "role": "actor",
            "actor_id": 0,
            "generation": 1,
            "host": "h",
            "pid": 8,
            "wall_clock": 101.5,
            "trace_id": "tid",
            "seq": 1,
            "metrics": {"env_steps_per_s": 512.0, "Sebulba/param_staleness_steps": 2.0},
        }
    )
    timeline.write_text("".join(json.dumps(r) + "\n" for r in rows))

    summary = trace_summary.summarize(str(timeline))
    assert summary["trace_id"] == "tid" and summary["rows"] == 3
    slots = summary["slots"]
    assert list(slots) == ["learner0", "actor0"]  # learner sorts first
    assert slots["learner0"]["rates"]["grad_steps_per_s"] == 40.0  # peak, not last
    assert slots["learner0"]["publish_apply_ms_mean"] == pytest.approx(3.5)
    assert slots["actor0"]["generations"] == [1]
    table = trace_summary.format_fleet_table(summary)
    assert "pub->apply_ms" in table and "learner0" in table

    # a merged multi-pid chrome trace groups phases per process
    doc = merge_chrome_traces(
        [
            ({"role": "learner", "actor_id": 0, "pid": 7},
             [{"name": "Time/update", "ph": "X", "pid": 0, "tid": 0, "ts": 1, "dur": 1000, "args": {"depth": 0}}]),
            ({"role": "actor", "actor_id": 0, "pid": 8},
             [{"name": "Time/env_interaction", "ph": "X", "pid": 0, "tid": 0, "ts": 1, "dur": 2000, "args": {"depth": 0}}]),
        ]
    )
    trace_path = tmp_path / "trace_fleet.json"
    trace_path.write_text(json.dumps(doc))
    merged = trace_summary.summarize(str(trace_path))
    assert set(merged["phases"]) == {
        "[learner (pid 7)] Time/update",
        "[actor0 (pid 8)] Time/env_interaction",
    }


# ------------------------------------------------------------------ slow e2e
@pytest.mark.slow
def test_fleet_two_actor_launcher_e2e(tmp_path):
    """The acceptance run: a real 2-actor SAC launcher topology exports one
    merged timeline with rows from every role, ships every process's spans into
    ONE Perfetto file, and `obs.top --once` renders the snapshot."""
    fleet_dir = tmp_path / "fleet"
    overrides = [
        "exp=sac_decoupled",
        "env=continuous_dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.hidden_size=8",
        "algo.per_rank_batch_size=8",
        "algo.learning_starts=4",
        "algo.total_steps=16",
        "buffer.size=256",
        "dry_run=False",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.run_test=False",
        "checkpoint.every=100000",
        "checkpoint.save_last=False",
        "metric.log_every=4",
        "buffer.memmap=False",
        f"log_root={tmp_path}/logs",
        "distributed.num_actors=2",
        "distributed.connect_timeout_s=30",
        "obs.enabled=True",  # tracers on -> every process ships spans
        "obs.fleet.interval_s=0.5",
        f"obs.fleet.dir={fleet_dir}",
    ]
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        SHEEPRL_TPU_QUIET="1",
    )
    env.pop(FLEET_ENV_VAR, None)
    env.pop(TRACE_ID_ENV_VAR, None)
    proc = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.sebulba", *overrides],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, f"launcher failed rc={proc.returncode}:\n{proc.stdout[-4000:]}"

    timeline = fleet_dir / "timeline.jsonl"
    assert timeline.is_file(), f"no fleet timeline; launcher output:\n{proc.stdout[-2000:]}"
    rows = [json.loads(line) for line in timeline.read_text().splitlines() if line.strip()]
    assert rows, "fleet timeline is empty"
    slots = {f"{r['role']}{r['actor_id']}" for r in rows}
    assert {"learner0", "actor0", "actor1"} <= slots, f"missing roles: {slots}"
    trace_ids = {r["trace_id"] for r in rows}
    assert len(trace_ids) == 1, f"rows not correlated under one trace id: {trace_ids}"
    for row in rows:
        assert set(ROW_TAG_KEYS) <= set(row)

    # ONE Perfetto file spanning all three processes' real pids.
    trace_path = fleet_dir / "trace_fleet.json"
    assert trace_path.is_file(), f"no merged trace; launcher output:\n{proc.stdout[-2000:]}"
    doc = json.loads(trace_path.read_text())
    pids = {e.get("pid") for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert len(pids) >= 3, f"merged trace spans {len(pids)} pids, expected >= 3"
    row_pids = {r["pid"] for r in rows}
    assert pids <= row_pids, "trace pids are not the exporters' real OS pids"

    # the live view renders it
    top = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.obs.top", str(fleet_dir), "--once"],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=60,
    )
    assert top.returncode == 0, f"obs.top --once failed:\n{top.stdout}"
    assert "learner0" in top.stdout and "actor1" in top.stdout


# ------------------------------------------------- exporter loop (busy-poll fix)
def test_exporter_answers_dump_fast_and_closes_fast_on_long_interval(tmp_path):
    """Regression for the ``Event.wait(0.05)`` busy poll: the export thread now
    sleeps in ``select()`` on the channel socket, so with a 60 s flush interval
    an inbound dump request is still answered in well under a second, and
    ``close()`` returns within the ``_POLL_CAP_S`` re-check bound rather than a
    full interval."""
    agg = FleetAggregator(str(tmp_path / "fleet"))
    try:
        exp = _exporter(agg, "learner", interval_s=60.0)
        try:
            assert exp.flush()
            _wait_for(lambda: agg.rows_written >= 1, msg="row ingested")

            t0 = time.monotonic()
            bundle = agg.collect_blackboxes("latency_probe")
            dump_latency = time.monotonic() - t0
            assert bundle is not None
            # generous bound for loaded CI hosts; the regression this guards
            # against is a full 60 s interval of latency
            assert dump_latency < 10.0, (
                f"dump round trip took {dump_latency:.2f}s against a 60s flush "
                "interval — the export loop is not waking on inbound traffic"
            )
        finally:
            t0 = time.monotonic()
            exp.close()
            close_latency = time.monotonic() - t0
        assert close_latency < 5.0, (
            f"close() took {close_latency:.2f}s — the export thread is not "
            "re-checking the stop flag"
        )
    finally:
        agg.close()


# ------------------------------------------------- PR-19: rotation + goodput
def test_timeline_rotation_bounds_disk_and_loses_no_recent_rows(tmp_path):
    """Size-capped timeline (``obs.fleet.max_timeline_mb``): crossing the cap
    renames the live file to ``timeline.jsonl.1`` and starts fresh — disk stays
    bounded at ~2x the cap, and the union of both generations still carries the
    most recent rows for every slot."""
    cap_bytes = 2048
    agg = FleetAggregator(str(tmp_path / "fleet"), max_timeline_mb=cap_bytes / (1024 * 1024))
    try:
        assert agg.max_timeline_bytes == cap_bytes
        exp = _exporter(agg, "learner")
        flushes = 0
        # rows are a few hundred bytes: enough flushes to cross the cap twice
        for i in range(24):
            exp.gauge("Perf/mfu", 0.1 + i * 0.01)
            assert exp.flush()
            flushes += 1
            _wait_for(lambda: agg.rows_written >= flushes, msg=f"row {flushes}")
        exp.close()

        rotated = pathlib.Path(agg.rotated_timeline_path)
        live = pathlib.Path(agg.timeline_path)
        assert rotated.exists(), "cap crossed but no rotated generation"
        assert live.stat().st_size < cap_bytes
        assert rotated.stat().st_size < cap_bytes + 1024, "rotated file way past cap"

        live_rows = [json.loads(line) for line in live.read_text().splitlines() if line]
        rot_rows = [json.loads(line) for line in rotated.read_text().splitlines() if line]
        seqs = sorted(r["seq"] for r in rot_rows + live_rows)
        # rotation drops the OLDEST generation only: the newest rows survive
        assert seqs[-1] == max(seqs) and len(seqs) == len(set(seqs))
        assert live_rows, "live file empty after rotation"
        assert live_rows[-1]["seq"] == max(seqs)
    finally:
        agg.close()


def test_top_tail_rebuild_reads_across_rotation_boundary(tmp_path):
    """Regression (PR-19 satellite): with snapshot.json missing, ``obs.top``
    must rebuild from BOTH timeline generations — a slot whose last row landed
    before the rotation still shows up, and a slot written in both generations
    resolves to its newest (live-file) row."""
    fleet_dir = tmp_path / "fleet"
    fleet_dir.mkdir()

    def _row(role, actor_id, seq, **metrics):
        row = {k: None for k in ROW_TAG_KEYS}
        row.update(role=role, actor_id=actor_id, generation=0, pid=7, wall_clock=time.time(), seq=seq)
        row["metrics"] = metrics
        return json.dumps(row) + "\n"

    # rotated generation: an actor slot that never wrote again + a stale learner row
    (fleet_dir / "timeline.jsonl.1").write_text(
        _row("actor", 1, 1, env_steps_per_s=9.0) + _row("learner", 0, 2, grad_steps_per_s=1.0)
    )
    # live generation: the learner's newer row must win over its rotated one
    (fleet_dir / "timeline.jsonl").write_text(
        _row("learner", 0, 3, grad_steps_per_s=5.5, **{"Perf/mfu": 0.42, "Perf/goodput": 0.87})
    )

    snap = fleet_top.load_snapshot(str(fleet_dir))
    assert snap is not None and snap.get("rebuilt_from_timeline")
    assert set(snap["processes"]) == {"actor1", "learner0"}
    assert snap["processes"]["learner0"]["metrics"]["grad_steps_per_s"] == 5.5

    table = fleet_top.format_top(snap)
    assert "actor1" in table and "learner0" in table
    # MFU / GOODPUT columns render the Perf/* gauges (MFU as a percentage)
    assert "MFU%" in table and "GOODPUT" in table
    assert "42.0" in table and "0.87" in table


def test_goodput_rollup_written_at_close(tmp_path):
    """``FleetAggregator.close()`` writes goodput.json: per-slot Perf gauges +
    restart downtime from inter-generation timeline gaps, and a fleet section
    naming the lowest-goodput slot as the ceiling."""
    agg = FleetAggregator(str(tmp_path / "fleet"))
    learner = _exporter(agg, "learner")
    learner.gauge("Perf/goodput", 0.9)
    learner.gauge("Perf/mfu", 0.33)
    learner.gauge("perf_anomalies", 1.0)
    assert learner.flush()
    gen0 = _exporter(agg, "actor", actor_id=1, generation=0)
    assert gen0.flush()
    _wait_for(lambda: agg.rows_written >= 2, msg="gen0 rows")
    gen0.close()
    time.sleep(0.2)  # restart gap -> downtime in the rollup
    gen1 = _exporter(agg, "actor", actor_id=1, generation=1)
    gen1.gauge("Perf/goodput", 0.5)
    assert gen1.flush()
    _wait_for(lambda: agg.rows_written >= 3, msg="gen1 row")
    learner.close()
    gen1.close()
    agg.close()

    report = json.load(open(pathlib.Path(agg.goodput_path)))
    slots = report["slots"]
    assert {"learner0", "actor1"} <= set(slots)
    assert slots["learner0"]["goodput"] == 0.9
    assert slots["learner0"]["mfu"] == 0.33
    assert slots["learner0"]["anomalies"] == 1.0
    assert slots["actor1"]["generations"] == 2
    assert slots["actor1"]["restart_downtime_s"] >= 0.15
    fleet = report["fleet"]
    assert fleet["min_goodput"] == 0.5
    assert fleet["ceiling_slot"] == "actor1", "straggler attribution wrong"
    assert fleet["anomalies"] == 1.0
