"""TrainingMonitor: no-op fast path, capture window, and the PPO end-to-end smoke run
from the acceptance criteria (Chrome trace + Time/Memory/Compile metrics, no recompile
warnings)."""

import json
import pathlib
import warnings

import pytest

from sheeprl_tpu.obs import TrainingMonitor, tracer
from sheeprl_tpu.obs.watchdog import RecompileWarning
from sheeprl_tpu.utils.logger import TensorBoardLogger


def test_disabled_monitor_is_noop(tmp_path):
    m = TrainingMonitor({"obs": {"enabled": False}}, str(tmp_path))
    assert not m.enabled
    assert tracer.get_active() is None  # no global tracer installed
    m.advance()
    assert m.metrics() == {}
    m.close()
    assert not list(tmp_path.iterdir())  # no trace export, no xprof dir

    class _Rec:
        def __init__(self):
            self.calls = []

        def log_metrics(self, metrics, step):
            self.calls.append((metrics, step))

    rec = _Rec()
    m.log_metrics(rec, {"a": 1.0}, 7)  # disabled monitor still forwards to the logger
    assert rec.calls == [({"a": 1.0}, 7)]


def test_enabled_monitor_spans_and_close(tmp_path):
    m = TrainingMonitor({"obs": {"enabled": True, "xprof_annotations": False}}, str(tmp_path), rank=0)
    try:
        assert tracer.get_active() is m.tracer
        m.advance()
        with m.span("Time/phase"):
            pass
        m.advance()
        out = m.metrics()
        assert "Time/phase/p50" in out
        assert "Compile/recompiles" in out
    finally:
        m.close()
    assert tracer.get_active() is None
    doc = json.load(open(tmp_path / "trace.json"))
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "Time/phase" in names
    assert "Time/update" in names  # advance() brackets each update in a top-level span
    m.close()  # idempotent


def test_rank_nonzero_trace_filename(tmp_path):
    m = TrainingMonitor({"obs": {"enabled": True, "xprof_annotations": False, "watchdog": False}}, str(tmp_path), rank=3)
    m.close()
    assert (tmp_path / "trace_rank3.json").is_file()


def test_capture_steps_validation(tmp_path):
    with pytest.raises(ValueError, match="capture_steps"):
        TrainingMonitor({"obs": {"enabled": True, "capture_steps": [3, 1]}}, str(tmp_path), rank=0)


def _tiny_ppo_args(tmp_path, extra=()):
    return [
        "exp=ppo",
        "env=discrete_dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.mlp_features_dim=8",
        "algo.total_steps=64",
        "algo.run_test=False",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
        "metric.log_every=1",
        f"log_root={tmp_path}",
        "buffer.memmap=False",
        *extra,
    ]


def test_ppo_smoke_with_observability(tmp_path, monkeypatch):
    from sheeprl_tpu.cli import run

    captured = []
    orig = TensorBoardLogger.log_metrics

    def _rec(self, metrics, step):
        captured.append(dict(metrics))
        orig(self, metrics, step)

    monkeypatch.setattr(TensorBoardLogger, "log_metrics", _rec)

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        run(
            _tiny_ppo_args(
                tmp_path,
                ["obs.enabled=True", "obs.telemetry_interval=0.0", "obs.capture_steps=[2,3]"],
            )
        )

    # (c) zero post-warmup recompile warnings
    assert not [w for w in caught if issubclass(w.category, RecompileWarning)]

    # (b) per-phase histogram metrics + memory/compile scalars reached the logger
    keys = set().union(*captured)
    assert "Time/env_interaction_time/p50" in keys
    assert "Time/train_time/p95" in keys
    assert "Time/h2d_transfer/p99" in keys
    assert any(k.startswith("Memory/") for k in keys)
    assert "Compile/recompiles" in keys and "Compile/total_compiles" in keys
    assert captured[-1]["Compile/recompiles"] == 0.0

    # (a) a valid Chrome-trace JSON in the run's version_* dir
    traces = list(pathlib.Path(tmp_path).rglob("version_*/trace.json"))
    assert len(traces) == 1
    doc = json.load(open(traces[0]))
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert events and all("ts" in e and "dur" in e and "pid" in e for e in events)
    names = {e["name"] for e in events}
    assert {"Time/env_interaction_time", "Time/train_time", "Time/h2d_transfer", "Time/update"} <= names

    # the programmatic capture window wrote an XProf trace
    assert list(pathlib.Path(tmp_path).rglob("xprof/**/*.xplane.pb"))

    # the monitor deactivated its tracer on close
    assert tracer.get_active() is None


def test_ppo_smoke_observability_disabled_leaves_no_artifacts(tmp_path):
    from sheeprl_tpu.cli import run

    run(_tiny_ppo_args(tmp_path))
    assert not list(pathlib.Path(tmp_path).rglob("trace.json"))
    assert not list(pathlib.Path(tmp_path).rglob("xprof"))
    assert tracer.get_active() is None
