"""Recompile watchdog: warmup compiles are free, post-warmup cache misses are counted."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.obs.watchdog import RecompileWatchdog


@pytest.fixture()
def watchdog():
    w = RecompileWatchdog()
    yield w
    w.close()


def test_counts_exactly_one_miss_after_warmup(watchdog):
    @jax.jit
    def f(x):
        return x * 2

    # Pre-stage inputs so the only compile the new shape triggers is f's own.
    x3 = jax.device_put(np.ones(3, dtype=np.float32))
    x5 = jax.device_put(np.ones(5, dtype=np.float32))
    jax.block_until_ready(f(x3))  # warmup compile
    watchdog.mark_warm()
    assert watchdog.recompiles == 0

    jax.block_until_ready(f(x3))  # cache hit
    assert watchdog.recompiles == 0
    assert watchdog.poll_new() == 0

    jax.block_until_ready(f(x5))  # new shape -> exactly one cache miss
    assert watchdog.recompiles == 1
    assert watchdog.poll_new() == 1
    assert watchdog.poll_new() == 0  # drained
    assert watchdog.metrics()["Compile/recompiles"] == 1.0
    assert watchdog.metrics()["Compile/total_compiles"] >= 2.0


def test_closed_watchdog_stops_counting(watchdog):
    watchdog.mark_warm()
    watchdog.close()

    @jax.jit
    def g(x):
        return jnp.sin(x)

    jax.block_until_ready(g(jax.device_put(np.ones(7, dtype=np.float32))))
    assert watchdog.recompiles == 0
