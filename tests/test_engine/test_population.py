"""Population Anakin (``sheeprl_tpu/engine/population.py``): the ISSUE-8
correctness contracts.

* ``population.size=1`` is BIT-IDENTICAL to plain Anakin (params + metrics): the
  member axis runs through ``lax.scan`` whose body is the unbatched program;
* K members with identical hyperparameters but different seeds match K separate
  single-member dispatches member-for-member, bitwise, for PPO and SAC
  (including the per-member ring counters/stamps);
* ``algo.population.sweep`` maps hyperparameters across members — a swept
  learning rate of 0 freezes exactly that member, a swept ``ent_coef`` changes
  exactly the swept members' updates;
* ``AnakinFutures.drain`` reduces member-axis metric leaves into
  ``Population/<metric>/{member_i,median,best}`` rows without extra host syncs;
* CLI e2e: population train + resume (with a different log cadence) for both
  algos, preset composition, and single-member blackbox replay.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.config.core import compose
from sheeprl_tpu.envs.jax import make_jax_env
from sheeprl_tpu.engine.population import (
    PopulationSpec,
    member_keys,
    population_rows,
    population_transform,
    set_injected_lr,
    slice_member,
    stack_members,
)
from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh

PPO_POP_ARGS = [
    "exp=ppo",
    "env=jax_cartpole",
    "algo.anakin=True",
    "algo.mlp_keys.encoder=[state]",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=8",
    "algo.update_epochs=1",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.encoder.mlp_features_dim=8",
]

SAC_POP_ARGS = [
    "exp=sac",
    "env=jax_pendulum",
    "algo.anakin=True",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=8",
    "algo.per_rank_batch_size=8",
    "algo.learning_starts=8",
    "algo.total_steps=64",
    "algo.anakin_steps_per_dispatch=8",
    "buffer.size=256",
]


def standard_args(tmp_path, extra=()):
    return [
        "dry_run=True",
        "env.num_envs=2",
        "env.capture_video=False",
        "checkpoint.every=1",
        "checkpoint.save_last=True",
        "metric.log_every=1",
        f"log_root={tmp_path}",
        "buffer.memmap=False",
        "algo.run_test=False",
        *extra,
    ]


def _ckpts(tmp_path):
    return sorted(tmp_path.rglob("ckpt_*"), key=lambda p: p.stat().st_mtime)


def assert_trees_equal(a, b, b_member=None, label=""):
    """Bitwise pytree equality; ``b_member`` compares against b's member slice."""
    for (path, la), lb in zip(jax.tree_util.tree_leaves_with_path(a), jax.tree.leaves(b)):
        rb = np.asarray(lb)[b_member] if b_member is not None else np.asarray(lb)
        np.testing.assert_array_equal(
            np.asarray(la), rb, err_msg=f"{label} diverged at {jax.tree_util.keystr(path)}"
        )


# ------------------------------------------------------------------------- PPO
def _ppo_setup(num_envs=2, inject_lr=False):
    cfg = compose(
        overrides=PPO_POP_ARGS + [f"env.num_envs={num_envs}", "env.capture_video=False", "buffer.memmap=False"]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.engine.anakin import make_ppo_anakin_iteration

    env = make_jax_env("cartpole")
    env_params = env.default_params()
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    agent, params = build_agent(ctx, env.action_space(env_params), obs_space, cfg)
    fns = PPOTrainFns(ctx, agent, cfg, ["state"], 4, inject_lr=inject_lr)
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, "state")
    return cfg, env, env_params, agent, fns, iteration


def _ppo_carries(env, env_params, agent, fns, members, num_envs=2, base_params=None, lr_values=None):
    """Per-member carries with distinct-but-deterministic params (the shared
    init scaled per member — structure-preserving, no re-init plumbing needed),
    member-folded env reset keys and the documented ``member_keys`` streams."""
    from sheeprl_tpu.engine.anakin import init_episode_stats, reset_envs

    base_key = jax.random.PRNGKey(3)
    keys = member_keys(base_key, members)
    carries = []
    for m in range(members):
        # distinct-but-deterministic per-member params: scale the shared init
        p = jax.tree.map(lambda x, s=m: x * (1.0 + 0.05 * s) if jnp.issubdtype(x.dtype, jnp.floating) else x,
                         base_params)
        o = fns.opt.init(p)
        if lr_values is not None:
            o = set_injected_lr(o, lr_values[m])
        env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.fold_in(jax.random.PRNGKey(7), m))
        carries.append(
            {
                "params": p,
                "opt_state": o,
                "env_state": env_state,
                "obs": obs0,
                "key": keys[m],
                "episode_stats": init_episode_stats(num_envs),
            }
        )
    return carries


def test_population_size1_bit_identical_to_plain():
    """The K=1 population dispatch and the plain dispatch produce EXACTLY the
    same params and metrics from the same initial carry."""
    cfg, env, env_params, agent, fns, iteration = _ppo_setup()
    base_params = _fresh_ppo_params(cfg, env, env_params)
    (carry,) = _ppo_carries(env, env_params, agent, fns, 1, base_params=base_params)
    plain_carry, plain_metrics = jax.jit(iteration)(carry, 0.2, 0.01)
    pop = jax.jit(population_transform(iteration, vectorize=False, n_args=2))
    pop_carry, pop_metrics = pop(
        stack_members([carry]), jnp.full((1,), 0.2, jnp.float32), jnp.full((1,), 0.01, jnp.float32)
    )
    assert_trees_equal(plain_carry, pop_carry, b_member=0, label="carry")
    assert_trees_equal(plain_metrics, pop_metrics, b_member=0, label="metrics")


def test_population_members_match_single_runs_ppo():
    """K members (same hyperparams, different seeds/inits) match K separate
    single-member dispatches member-for-member, bitwise — params, optimizer
    state, env states and metrics."""
    cfg, env, env_params, agent, fns, iteration = _ppo_setup()
    base_params = _fresh_ppo_params(cfg, env, env_params)
    members = 3
    carries = _ppo_carries(env, env_params, agent, fns, members, base_params=base_params)
    pop = jax.jit(population_transform(iteration, vectorize=False, n_args=2))
    pop_carry, pop_metrics = pop(
        stack_members(carries),
        jnp.full((members,), 0.2, jnp.float32),
        jnp.full((members,), 0.01, jnp.float32),
    )
    single = jax.jit(iteration)
    for m in range(members):
        s_carry, s_metrics = single(carries[m], 0.2, 0.01)
        assert_trees_equal(s_carry, pop_carry, b_member=m, label=f"member {m} carry")
        assert_trees_equal(s_metrics, pop_metrics, b_member=m, label=f"member {m} metrics")


def _fresh_ppo_params(cfg, env, env_params):
    from sheeprl_tpu.algos.ppo.agent import build_agent

    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=123)
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    _, params = build_agent(ctx, env.action_space(env_params), obs_space, cfg)
    return params


def test_population_ent_coef_sweep_changes_swept_member_only_inputs():
    """Two members with the SAME seed/init but different ent_coef: the sweep
    reaches the update (params diverge across members); a zero-vs-zero control
    stays identical."""
    cfg, env, env_params, agent, fns, iteration = _ppo_setup()
    base_params = _fresh_ppo_params(cfg, env, env_params)
    carries = _ppo_carries(env, env_params, agent, fns, 1, base_params=base_params) * 2  # same member twice
    pop = jax.jit(population_transform(iteration, vectorize=False, n_args=2))
    stacked = stack_members(carries)
    swept_carry, _ = pop(stacked, jnp.full((2,), 0.2, jnp.float32), jnp.asarray([0.0, 0.5], jnp.float32))
    p0 = jax.device_get(slice_member(swept_carry["params"], 0))
    p1 = jax.device_get(slice_member(swept_carry["params"], 1))
    assert any(
        not np.array_equal(a, b) for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1))
    ), "ent_coef sweep did not reach the members' updates"
    same_carry, _ = pop(stacked, jnp.full((2,), 0.2, jnp.float32), jnp.zeros((2,), jnp.float32))
    assert_trees_equal(slice_member(same_carry["params"], 0), same_carry["params"], b_member=1, label="control")


def test_population_lr_sweep_freezes_zero_lr_member():
    """optimizer.lr sweep via inject_hyperparams: the lr=0 member's params stay
    bit-identical to its init while the lr>0 member trains."""
    cfg, env, env_params, agent, fns, iteration = _ppo_setup(inject_lr=True)
    base_params = _fresh_ppo_params(cfg, env, env_params)
    carries = _ppo_carries(
        env, env_params, agent, fns, 2, base_params=base_params, lr_values=[0.0, 1e-3]
    )
    pop = jax.jit(population_transform(iteration, vectorize=False, n_args=2))
    new_carry, _ = pop(
        stack_members(carries), jnp.full((2,), 0.2, jnp.float32), jnp.zeros((2,), jnp.float32)
    )
    assert_trees_equal(carries[0]["params"], new_carry["params"], b_member=0, label="lr=0 member moved")
    p1_new = jax.device_get(slice_member(new_carry["params"], 1))
    p1_old = jax.device_get(carries[1]["params"])
    assert any(
        not np.array_equal(a, b) for a, b in zip(jax.tree.leaves(p1_new), jax.tree.leaves(p1_old))
    ), "lr=1e-3 member did not train"


def test_population_vectorize_mode_matches_map_mode_closely():
    """`vectorize=True` (jax.vmap member axis) is the same training computation
    batched — numerically close to the bit-exact map mode, not guaranteed
    bitwise (XLA may fuse batched ops differently; documented trade-off)."""
    cfg, env, env_params, agent, fns, iteration = _ppo_setup()
    base_params = _fresh_ppo_params(cfg, env, env_params)
    carries = _ppo_carries(env, env_params, agent, fns, 2, base_params=base_params)
    coefs = (jnp.full((2,), 0.2, jnp.float32), jnp.full((2,), 0.01, jnp.float32))
    map_carry, map_metrics = jax.jit(population_transform(iteration, vectorize=False, n_args=2))(
        stack_members(carries), *coefs
    )
    vmap_carry, vmap_metrics = jax.jit(population_transform(iteration, vectorize=True, n_args=2))(
        stack_members(carries), *coefs
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(map_carry["params"])),
                    jax.tree.leaves(jax.device_get(vmap_carry["params"]))):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
    for k in map_metrics:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(map_metrics[k])), np.asarray(jax.device_get(vmap_metrics[k])),
            rtol=1e-3, atol=1e-4, err_msg=k,
        )


# ------------------------------------------------------------------------- SAC
def test_population_members_match_single_runs_sac():
    """SAC population dispatch vs per-member single dispatches: params, ring
    arrays (incl. write stamps), rows_added/gstep counters and metrics all match
    bitwise per member."""
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.data.device_buffer import STAMP_KEY, DeviceTransitionRing
    from sheeprl_tpu.engine.anakin import init_episode_stats, make_sac_anakin_dispatch, reset_envs

    cfg = compose(
        overrides=SAC_POP_ARGS + ["env.num_envs=2", "env.capture_video=False", "buffer.memmap=False"]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    env = make_jax_env("pendulum")
    env_params = env.default_params()
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    act_space = env.action_space(env_params)
    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    params = jax.tree.map(jnp.copy, params)
    ring = DeviceTransitionRing(
        16, 2, {"obs": ((3,), jnp.float32), "next_obs": ((3,), jnp.float32),
                "actions": ((1,), jnp.float32), "rewards": ((1,), jnp.float32),
                "dones": ((1,), jnp.float32)}
    )
    actor_opt, critic_opt, alpha_opt, builder = make_sac_anakin_dispatch(
        env, env_params, actor, critic, cfg, act_space, ring, 4
    )
    members = 2
    keys = member_keys(jax.random.PRNGKey(1), members)
    carries = []
    for m in range(members):
        p = jax.tree.map(
            lambda x, s=m: x * (1.0 + 0.05 * s) if jnp.issubdtype(x.dtype, jnp.floating) else x, params
        )
        env_state, obs0 = reset_envs(env, env_params, 2, jax.random.fold_in(jax.random.PRNGKey(0), m))
        carries.append(
            {
                "params": p,
                "opt_state": {
                    "actor": actor_opt.init(p["actor"]),
                    "critic": critic_opt.init(p["critic"]),
                    "alpha": alpha_opt.init(p["log_alpha"]),
                },
                "env_state": env_state,
                "obs": obs0,
                "ring": jax.tree.map(jnp.copy, ring.arrays),
                "rows_added": jnp.zeros((), jnp.int32),
                "gstep": jnp.zeros((), jnp.int32),
                "key": keys[m],
                "episode_stats": init_episode_stats(2),
            }
        )
    program = builder(5, 1, True)
    pop_carry, pop_metrics = jax.jit(population_transform(program, vectorize=False))(stack_members(carries))
    single = jax.jit(program)
    for m in range(members):
        s_carry, s_metrics = single(carries[m])
        assert_trees_equal(s_carry, pop_carry, b_member=m, label=f"member {m} carry")
        assert_trees_equal(s_metrics, pop_metrics, b_member=m, label=f"member {m} metrics")
    # counters and stamps advanced per member
    assert np.all(np.asarray(jax.device_get(pop_carry["rows_added"])) == 5)
    assert np.all(np.asarray(jax.device_get(pop_carry["gstep"])) == 5)
    stamps = np.asarray(jax.device_get(pop_carry["ring"][STAMP_KEY]))  # [K, n_envs, cap, 1]
    for m in range(members):
        np.testing.assert_array_equal(stamps[m, :, :5, 0], np.broadcast_to(np.arange(5), (2, 5)))


# ------------------------------------------------------------------- spec/drain
def test_population_spec_validation():
    cfg = compose(
        overrides=PPO_POP_ARGS
        + ["algo.population.size=2", "env.capture_video=False", "buffer.memmap=False"]
    )
    spec = PopulationSpec.from_cfg(cfg, "ppo")
    assert spec.enabled and spec.size == 2 and not spec.sweep

    cfg.algo.population.sweep = {"ent_coef": [0.0, 0.1]}
    assert PopulationSpec.from_cfg(cfg, "ppo").sweep == {"ent_coef": (0.0, 0.1)}

    cfg.algo.population.sweep = {"ent_coef": [0.0]}
    with pytest.raises(ValueError, match="one value per member"):
        PopulationSpec.from_cfg(cfg, "ppo")

    cfg.algo.population.sweep = {"gamma": [0.9, 0.99]}
    with pytest.raises(ValueError, match="not sweepable"):
        PopulationSpec.from_cfg(cfg, "ppo")

    # nested CLI spelling flattens: sweep.critic.optimizer.lr -> critic.optimizer.lr
    cfg.algo.population.sweep = {"critic": {"optimizer": {"lr": [1e-3, 3e-4]}}}
    assert PopulationSpec.from_cfg(cfg, "sac").sweep == {"critic.optimizer.lr": (1e-3, 3e-4)}


def test_member_keys_contract():
    base = jax.random.PRNGKey(5)
    keys = member_keys(base, 3)
    np.testing.assert_array_equal(np.asarray(keys[0]), np.asarray(base))  # member 0 = base stream
    np.testing.assert_array_equal(np.asarray(keys[1]), np.asarray(jax.random.fold_in(base, 1)))
    assert not np.array_equal(np.asarray(keys[1]), np.asarray(keys[2]))


def test_anakin_futures_drain_population_reduction():
    """Member-axis metric leaves drain as Population/<key>/{member_i,median,best}
    (min for Loss/*, max for reward-like), the plain key logs the cross-member
    mean, and per-member episode sums derive per-member rew_avg."""
    from sheeprl_tpu.engine.anakin import AnakinFutures
    from sheeprl_tpu.utils.metric import MetricAggregator

    futures = AnakinFutures()
    aggregator = MetricAggregator({})
    metrics = {
        "Loss/value_loss": jnp.asarray([1.0, 3.0, 2.0]),
        "Health/grad_norm": jnp.asarray([0.1, 0.2, 0.3]),
        "Episodes/return_sum": jnp.asarray([10.0, 0.0, 30.0]),
        "Episodes/len_sum": jnp.asarray([20.0, 0.0, 30.0]),
        "Episodes/count": jnp.asarray([2.0, 0.0, 1.0]),
    }
    futures.track(metrics, env_steps=300, grad_steps=30)
    out = futures.drain(aggregator)

    assert out["Population/Loss/value_loss/member_1"] == 3.0
    assert out["Population/Loss/value_loss/median"] == 2.0
    assert out["Population/Loss/value_loss/best"] == 1.0  # Loss: best = min
    # Health: members + median, no "best"
    assert out["Population/Health/grad_norm/median"] == pytest.approx(0.2)
    assert "Population/Health/grad_norm/best" not in out
    # per-member episode means; member 1 had no episodes -> no row
    assert out["Population/Rewards/rew_avg/member_0"] == pytest.approx(5.0)
    assert out["Population/Rewards/rew_avg/member_2"] == pytest.approx(30.0)
    assert "Population/Rewards/rew_avg/member_1" not in out
    assert out["Population/Rewards/rew_avg/best"] == pytest.approx(30.0)  # reward: best = max
    agg = aggregator.compute()
    assert agg["Loss/value_loss"] == pytest.approx(2.0)  # plain key = member mean
    assert agg["Rewards/rew_avg"] == pytest.approx((5.0 + 30.0) / 2)


def test_population_rows_reduction_units():
    rows = population_rows("Loss/x", np.asarray([2.0, np.nan, 1.0]))
    assert rows["Population/Loss/x/best"] == 1.0 and "Population/Loss/x/member_1" not in rows
    rows = population_rows("Rewards/x", np.asarray([2.0, 5.0]))
    assert rows["Population/Rewards/x/best"] == 5.0 and rows["Population/Rewards/x/median"] == 3.5


# -------------------------------------------------------------------- CLI e2e
def test_ppo_population_cli_smoke_and_resume_with_new_cadence(tmp_path):
    """Population train + checkpoint, then resume the stacked carry with a
    DIFFERENT metric.log_every — the member axis round-trips through the
    CheckpointManager and the log cadence is free to change across runs — and
    finally the eval entry digs member 0's policy out of the stacked carry."""
    from sheeprl_tpu.cli import evaluate

    args = PPO_POP_ARGS + [
        "algo.total_steps=32",
        "algo.population.size=3",
        "algo.population.sweep.ent_coef=[0.0,0.01,0.1]",
    ]
    run(args + standard_args(tmp_path))
    ckpts = _ckpts(tmp_path)
    assert ckpts, "no checkpoint written"
    run(
        args
        + [f"checkpoint.resume_from={ckpts[-1]}"]
        + standard_args(tmp_path, extra=["metric.log_every=64"])
    )
    evaluate([f"checkpoint_path={_ckpts(tmp_path)[-1]}", "env.capture_video=False"])


@pytest.mark.slow
def test_sac_population_cli_smoke_and_resume(tmp_path):
    """Slow tier: the SAC population CLI round trip (the fast tier keeps the
    builder-level SAC member parity test + the PPO population CLI smoke, and CI
    runs its own population train+resume smoke)."""
    args = SAC_POP_ARGS + [
        "algo.population.size=2",
        "algo.population.sweep.critic.optimizer.lr=[0.001,0.0003]",
    ]
    extra = ["dry_run=False", "checkpoint.every=16", "metric.log_every=16"]
    run(args + standard_args(tmp_path, extra=extra))
    ckpts = _ckpts(tmp_path)
    assert ckpts, "no checkpoint written"
    run(
        args
        + [f"checkpoint.resume_from={ckpts[-1]}", "algo.total_steps=96"]
        + standard_args(tmp_path, extra=["dry_run=False", "checkpoint.every=16", "metric.log_every=32"])
    )


def test_population_exp_presets_compose():
    for exp, size in (("ppo_anakin_pop", 16), ("sac_anakin_pop", 16)):
        cfg = compose(overrides=[f"exp={exp}"])
        assert cfg.algo.anakin and cfg.env.jax.enabled
        assert int(cfg.algo.population.size) == size
        assert cfg.algo.mlp_keys.encoder == ["state"]


@pytest.mark.slow
def test_population_nan_injection_dumps_and_replays_single_member(tmp_path):
    """Slow tier (crash + dump + rebuild): strict-mode forensics for a
    population run — the blackbox stages the STACKED carry; --member replays one
    member's slice through the plain single-member program on CPU and reproduces
    the non-finite metrics."""
    from sheeprl_tpu.analysis.strict import NonFiniteError
    from sheeprl_tpu.obs import replay_blackbox

    with pytest.raises(NonFiniteError, match="inject_nan"):
        run(
            PPO_POP_ARGS
            + [
                "algo.population.size=2",
                "analysis.strict=True",
                "analysis.inject_nan=True",
            ]
            + standard_args(tmp_path, extra=["checkpoint.every=0", "checkpoint.save_last=False"])
        )
    dumps = list(tmp_path.rglob("blackbox"))
    assert dumps, "no blackbox directory written"
    outputs, nonfinite = replay_blackbox.replay(dumps[0], member=1)
    assert outputs.get("member") == 1
    assert nonfinite, "single-member replay did not reproduce the injected non-finite metrics"
