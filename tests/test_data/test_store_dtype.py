"""``buffer.store_dtype=bf16``: the ring's reduced-precision observation planes
(howto/precision.md, satellite of the precision tier).

Only ``obs``/``next_obs`` store at bf16 (STORE_DTYPE_KEYS); everything else is
bit-identical to a full-precision ring.  Sampled batches come back at the keys'
DECLARED dtype (f32) and must match the f32-stored ring within one bf16
rounding step — through both write paths (host ``add_step`` and the in-scan
writer) and the in-jit sample gather.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data.device_buffer import (
    STORE_DTYPE_KEYS,
    DeviceTransitionRing,
    resolve_store_dtype,
)

# bf16 has 8 mantissa bits: relative rounding error <= 2^-8 on O(1) values.
BF16_ATOL = 2 ** -7


def _specs(obs_dim=6, act_dim=2):
    return {
        "obs": ((obs_dim,), jnp.float32),
        "next_obs": ((obs_dim,), jnp.float32),
        "actions": ((act_dim,), jnp.float32),
        "rewards": ((1,), jnp.float32),
        "dones": ((1,), jnp.float32),
    }


def _step(rng, n_envs, obs_dim=6, act_dim=2):
    return {
        "obs": rng.standard_normal((1, n_envs, obs_dim)).astype(np.float32),
        "next_obs": rng.standard_normal((1, n_envs, obs_dim)).astype(np.float32),
        "actions": rng.standard_normal((1, n_envs, act_dim)).astype(np.float32),
        "rewards": rng.standard_normal((1, n_envs, 1)).astype(np.float32),
        "dones": np.zeros((1, n_envs, 1), np.float32),
    }


def _twin_rings(capacity=16, n_envs=2):
    """(f32-stored ring, bf16-stored ring) over identical specs."""
    return (
        DeviceTransitionRing(capacity, n_envs, _specs()),
        DeviceTransitionRing(capacity, n_envs, _specs(), store_dtype=jnp.bfloat16),
    )


def test_resolve_store_dtype_spellings_and_unknown():
    for spec in (None, "", "none", "null", "f32", "fp32", "float32"):
        assert resolve_store_dtype(spec) is None
    assert resolve_store_dtype("bf16") is jnp.bfloat16
    assert resolve_store_dtype("bfloat16") is jnp.bfloat16
    with pytest.raises(ValueError, match="fp8"):
        resolve_store_dtype("fp8")


def test_only_obs_planes_store_reduced():
    _, ring = _twin_rings()
    for k in STORE_DTYPE_KEYS:
        assert ring.arrays[k].dtype == jnp.bfloat16
    for k in ("actions", "rewards", "dones"):
        assert ring.arrays[k].dtype == jnp.float32


def test_add_step_and_gather_parity_with_f32_ring():
    n_envs, cap = 2, 16
    rng = np.random.default_rng(0)
    full, half = _twin_rings(cap, n_envs)

    for t in range(20):  # wraps the ring
        step = _step(rng, n_envs)
        full.add_step(step, position=t, rows_added=t)
        half.add_step(step, position=t, rows_added=t)

    key = jax.random.PRNGKey(0)
    filled = jnp.asarray(cap, jnp.int32)
    rows_added = jnp.asarray(20, jnp.int32)
    batch_f32, ages_f32 = jax.jit(full.make_sample_gather(8))(full.arrays, filled, rows_added, key)
    batch_bf16, ages_bf16 = jax.jit(half.make_sample_gather(8))(half.arrays, filled, rows_added, key)

    # sampled batches come back at the DECLARED dtype on both rings
    for k, batch in (("full", batch_f32), ("half", batch_bf16)):
        del k
        for arr in batch.values():
            assert arr.dtype == jnp.float32

    # non-obs planes are bit-identical; obs planes within one bf16 rounding step
    for k in ("actions", "rewards", "dones"):
        np.testing.assert_array_equal(np.asarray(batch_f32[k]), np.asarray(batch_bf16[k]))
    for k in STORE_DTYPE_KEYS:
        np.testing.assert_allclose(
            np.asarray(batch_f32[k]), np.asarray(batch_bf16[k]), atol=BF16_ATOL, rtol=BF16_ATOL
        )
        assert not np.array_equal(np.asarray(batch_f32[k]), np.asarray(batch_bf16[k])), (
            "bf16 storage should actually round — identical planes mean the cast never happened"
        )

    # same indices were drawn (same key), so staleness metrics agree exactly
    for k in ages_f32:
        np.testing.assert_array_equal(np.asarray(ages_f32[k]), np.asarray(ages_bf16[k]))


def test_scan_writer_round_trip_casts_on_write_and_back_on_sample():
    n_envs, cap = 2, 8
    rng = np.random.default_rng(1)
    _, ring = _twin_rings(cap, n_envs)
    write = ring.make_scan_writer()

    arrays = ring.arrays
    expect_obs = None
    for t in range(cap):
        step = _step(rng, n_envs)
        rows = {k: jnp.asarray(v[0]) for k, v in step.items()}
        arrays = jax.jit(write)(arrays, rows, jnp.asarray(t, jnp.int32))
        if t == cap - 1:
            expect_obs = step["obs"][0]

    assert arrays["obs"].dtype == jnp.bfloat16  # the writer casts to storage dtype

    gather = ring.make_sample_gather(4)
    batch, _ = jax.jit(gather)(
        arrays, jnp.asarray(cap, jnp.int32), jnp.asarray(cap, jnp.int32), jax.random.PRNGKey(2)
    )
    assert batch["obs"].dtype == jnp.float32

    # the last written row survives the bf16 round trip within one rounding step
    last = np.asarray(arrays["obs"][:, cap - 1].astype(jnp.float32)).reshape(n_envs, -1)
    np.testing.assert_allclose(last, expect_obs.reshape(n_envs, -1), atol=BF16_ATOL, rtol=BF16_ATOL)
