"""EpisodeBuffer semantics (reference: ``tests/test_data/test_episode_buffer.py``)."""

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EpisodeBuffer


def _episode_data(length, n_envs=1, end=True):
    term = np.zeros((length, n_envs, 1), dtype=np.float32)
    if end:
        term[-1] = 1
    return {
        "observations": np.arange(length, dtype=np.float32).reshape(length, 1, 1).repeat(n_envs, 1),
        "terminated": term,
        "truncated": np.zeros_like(term),
    }


def test_episode_buffer_add_complete_episode():
    eb = EpisodeBuffer(64, minimum_episode_length=2)
    eb.add(_episode_data(10))
    assert len(eb) == 10
    assert len(eb.buffer) == 1


def test_episode_buffer_open_episode_not_stored():
    eb = EpisodeBuffer(64, minimum_episode_length=2)
    eb.add(_episode_data(5, end=False))
    assert len(eb) == 0
    eb.add(_episode_data(3))
    assert len(eb) == 8  # chunks concatenated into one episode


def test_episode_buffer_too_short_raises():
    eb = EpisodeBuffer(64, minimum_episode_length=5)
    with pytest.raises(RuntimeError):
        eb.add(_episode_data(3))


def test_episode_buffer_eviction():
    eb = EpisodeBuffer(20, minimum_episode_length=2)
    for _ in range(4):
        eb.add(_episode_data(8))
    assert len(eb) <= 20
    assert len(eb.buffer) == 2


def test_episode_buffer_sample_shapes():
    eb = EpisodeBuffer(64, minimum_episode_length=2)
    eb.add(_episode_data(20))
    s = eb.sample(3, sequence_length=6, n_samples=2)
    assert s["observations"].shape == (2, 6, 3, 1)
    seq = s["observations"][0, :, 0, 0]
    assert np.allclose(np.diff(seq), 1)


def test_episode_buffer_prioritize_ends():
    eb = EpisodeBuffer(64, minimum_episode_length=2, prioritize_ends=True)
    eb.add(_episode_data(10))
    s = eb.sample(64, sequence_length=4)
    # With prioritised ends the last step must appear in some sampled sequence.
    assert (s["observations"] == 9).any()


def test_episode_buffer_sample_no_valid_raises():
    eb = EpisodeBuffer(64, minimum_episode_length=2)
    eb.add(_episode_data(3))
    with pytest.raises(RuntimeError):
        eb.sample(1, sequence_length=10)


def test_episode_buffer_memmap(tmp_path):
    eb = EpisodeBuffer(64, minimum_episode_length=2, memmap=True, memmap_dir=tmp_path / "eb")
    eb.add(_episode_data(6))
    assert len(eb) == 6
    s = eb.sample(2, sequence_length=3)
    assert s["observations"].shape == (1, 3, 2, 1)
