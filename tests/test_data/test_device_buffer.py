"""Device-resident replay mirror (``data/device_buffer.py``): the scatter/gather
round trip must reproduce exactly what the host buffer would have sampled."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, ReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import STAMP_KEY, DeviceReplayMirror, DeviceTransitionRing


def _row(rng, n_envs, t):
    return {
        "rgb": rng.integers(0, 255, (1, n_envs, 3, 8, 8), dtype=np.uint8),
        "rewards": np.full((1, n_envs, 1), float(t), np.float32),
        "is_first": np.zeros((1, n_envs, 1), np.float32),
    }


def _specs():
    return {"rgb": ((3, 8, 8), jnp.uint8), "rewards": ((1,), jnp.float32), "is_first": ((1,), jnp.float32)}


def test_mirror_matches_host_rows():
    n_envs, cap, seq = 3, 16, 4
    rng = np.random.default_rng(0)
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    rb.seed(0)
    mirror = DeviceReplayMirror(cap, n_envs, _specs())

    for t in range(25):  # wraps the ring
        row = _row(rng, n_envs, t)
        positions = [rb.buffer[e]._pos for e in range(n_envs)]
        mirror.add(row, list(range(n_envs)), positions)
        rb.add(row)
        if t % 7 == 3:  # uneven terminal adds: per-env cursors diverge
            sub = {k: v[:, :1] for k, v in _row(rng, n_envs, 100 + t).items()}
            mirror.add(sub, [1], [rb.buffer[1]._pos])
            rb.add(sub, indices=[1])

    # Every mirror row must equal the host row at the same (pos, env).
    for k in ("rgb", "rewards"):
        dev = mirror.host_rows(k)
        for e in range(n_envs):
            host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *dev.shape[2:])
            np.testing.assert_array_equal(dev[:, e], host, err_msg=f"{k} env {e}")

    # Index-sampled device gather == host rows at those indices.
    envs, starts = rb.sample_idx(8, seq)
    out = jax.jit(mirror.make_gather_fn(seq))(
        mirror.arrays, jnp.asarray(envs, jnp.int32), jnp.asarray(starts, jnp.int32)
    )
    for b in range(8):
        e, st = int(envs[b]), int(starts[b])
        host = np.asarray(rb.buffer[e]._buf["rewards"])[:, 0]
        expect = np.stack([host[(st + t) % cap] for t in range(seq)])
        np.testing.assert_array_equal(np.asarray(out["rewards"])[:, b], expect)


def test_sharded_mirror_parity_with_host():
    """dp>1 (env axis sharded over the CPU mesh's data axis): scatter, per-shard
    index sampling, and the shard_map gather must reproduce exactly what the host
    buffer would sample — the device path ≡ host path contract under DP."""
    from sheeprl_tpu.data.device_buffer import sample_index_block
    from sheeprl_tpu.parallel.mesh import build_mesh

    dp, n_envs, cap, seq, batch = 4, 8, 16, 4, 8
    mesh = build_mesh(data=dp, devices=jax.devices()[:dp])
    rng = np.random.default_rng(2)
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    rb.seed(2)
    mirror = DeviceReplayMirror(cap, n_envs, _specs(), mesh=mesh, dp=dp)

    for t in range(25):  # wraps the ring
        row = _row(rng, n_envs, t)
        positions = [rb.buffer[e]._pos for e in range(n_envs)]
        mirror.add(row, list(range(n_envs)), positions)
        rb.add(row)
        if t % 7 == 3:  # subset writes with per-env cursors diverging
            sub = {k: v[:, :1] for k, v in _row(rng, n_envs, 100 + t).items()}
            mirror.add(sub, [5], [rb.buffer[5]._pos])
            rb.add(sub, indices=[5])

    for k in ("rgb", "rewards"):
        dev = mirror.host_rows(k)
        for e in range(n_envs):
            host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *dev.shape[2:])
            np.testing.assert_array_equal(dev[:, e], host, err_msg=f"{k} env {e}")

    # Per-shard sampling keeps batch element j on the shard owning env j's block...
    envs, starts = sample_index_block(rb, batch, seq, n=3, dp=dp)
    e_local, b_local = n_envs // dp, batch // dp
    for g in range(3):
        for j in range(batch):
            assert envs[g, j] // e_local == j // b_local

    # Resume path under dp>1: a freshly-built sharded mirror rebuilt from the host
    # buffer must hold the same rows (and keep the env sharding).
    rebuilt = DeviceReplayMirror(cap, n_envs, _specs(), mesh=mesh, dp=dp)
    rebuilt.load_from(rb)
    for k in ("rgb", "rewards"):
        np.testing.assert_array_equal(rebuilt.host_rows(k), mirror.host_rows(k), err_msg=f"load_from {k}")
        assert rebuilt.arrays[k].sharding.spec == jax.sharding.PartitionSpec("data")

    # ...so the shard_map gather is shard-local and matches the host rows.
    gather = jax.jit(mirror.make_gather_fn(seq))
    out = gather(mirror.arrays, jnp.asarray(envs[0], jnp.int32), jnp.asarray(starts[0], jnp.int32))
    assert out["rewards"].sharding.spec == jax.sharding.PartitionSpec(None, "data")
    for b in range(batch):
        e, st = int(envs[0][b]), int(starts[0][b])
        for k in ("rgb", "rewards"):
            host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *np.asarray(out[k]).shape[2:])
            expect = np.stack([host[(st + t) % cap] for t in range(seq)])
            np.testing.assert_array_equal(np.asarray(out[k])[:, b], expect, err_msg=f"{k} b={b}")


def test_mirror_load_from_resume():
    n_envs, cap = 2, 8
    rng = np.random.default_rng(1)
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    for t in range(5):
        rb.add(_row(rng, n_envs, t))
    mirror = DeviceReplayMirror(cap, n_envs, _specs())
    mirror.load_from(rb)
    dev = mirror.host_rows("rewards")
    for e in range(n_envs):
        np.testing.assert_array_equal(dev[:5, e, 0], np.arange(5, dtype=np.float32))


# ---------------------------------------------------------------------------
# DeviceTransitionRing (SAC family): donated scatter, in-jit uniform sampling
# from a fixed key, and in-jit staleness — all bit-identical to the host buffer.
# ---------------------------------------------------------------------------

def _transition_row(rng, n_envs, t):
    return {
        "obs": rng.random((1, n_envs, 5)).astype(np.float32),
        "next_obs": rng.random((1, n_envs, 5)).astype(np.float32),
        "actions": rng.random((1, n_envs, 2)).astype(np.float32),
        "rewards": np.full((1, n_envs, 1), float(t), np.float32),
        "dones": np.zeros((1, n_envs, 1), np.float32),
    }


def _transition_specs():
    return {
        "obs": ((5,), jnp.float32),
        "next_obs": ((5,), jnp.float32),
        "actions": ((2,), jnp.float32),
        "rewards": ((1,), jnp.float32),
        "dones": ((1,), jnp.float32),
    }


def _filled_ring(n_envs=3, cap=16, steps=25, seed=0):
    """Host ReplayBuffer + DeviceTransitionRing fed the same rows (wrapping)."""
    rng = np.random.default_rng(seed)
    rb = ReplayBuffer(cap, n_envs, obs_keys=("obs",))
    rb.seed(seed)
    ring = DeviceTransitionRing(cap, n_envs, _transition_specs())
    for t in range(steps):
        row = _transition_row(rng, n_envs, t)
        ring.add_step(row, rb._pos, rb.rows_added)
        rb.add(row)
    return rb, ring


def test_transition_ring_matches_host_rows():
    n_envs, cap = 3, 16
    rb, ring = _filled_ring(n_envs, cap)
    for k in ("obs", "next_obs", "actions", "rewards", "dones"):
        dev = ring.host_rows(k)  # [cap, n_envs, *row_shape]
        np.testing.assert_array_equal(dev, rb[k], err_msg=k)
    # Write stamps match the host buffer's staleness bookkeeping row for row.
    stamps = ring.host_rows(STAMP_KEY)[:, :, 0]  # [cap, n_envs]
    for e in range(n_envs):
        np.testing.assert_array_equal(stamps[:, e], rb.row_stamps)


def test_transition_ring_in_jit_sampling_bit_identical_to_host():
    """Fixed key -> the in-jit sampled batch equals a host-side numpy gather at the
    indices the same computation produces, bit for bit — and is deterministic."""
    n_envs, cap, batch = 3, 16, 8
    rb, ring = _filled_ring(n_envs, cap)
    key = jax.random.PRNGKey(7)
    filled = len(rb)

    envs, rows = jax.jit(lambda f, k: ring.sample_indices(f, k, batch))(filled, key)
    sample_gather = ring.make_sample_gather(batch)
    batch1, ages1 = jax.jit(sample_gather)(ring.arrays, filled, rb.rows_added, key)
    batch2, _ = jax.jit(sample_gather)(ring.arrays, filled, rb.rows_added, key)

    envs, rows = np.asarray(envs), np.asarray(rows)
    assert rows.max() < filled
    for k in ("obs", "next_obs", "actions", "rewards", "dones"):
        host = rb[k][rows, envs]  # host storage is [cap, n_envs, ...]
        np.testing.assert_array_equal(np.asarray(batch1[k]), host, err_msg=k)
        np.testing.assert_array_equal(np.asarray(batch1[k]), np.asarray(batch2[k]))

    # In-jit staleness == the host buffer's definition (age = rows_added-1 - stamp).
    expect_ages = (rb.rows_added - 1) - rb.row_stamps[rows]
    assert float(ages1["Health/replay_age_mean"]) == expect_ages.mean()
    assert float(ages1["Health/replay_age_max"]) == expect_ages.max()


def test_transition_ring_resume_rebuild():
    n_envs, cap = 2, 8
    rb, ring = _filled_ring(n_envs, cap, steps=11, seed=3)
    rebuilt = DeviceTransitionRing(cap, n_envs, _transition_specs())
    rebuilt.load_from_transitions(
        {k: rb[k] for k in ("obs", "next_obs", "actions", "rewards", "dones")},
        stamps=rb.row_stamps,
    )
    for k in ("obs", "next_obs", "actions", "rewards", "dones", STAMP_KEY):
        np.testing.assert_array_equal(rebuilt.host_rows(k), ring.host_rows(k), err_msg=k)


def test_transition_ring_scan_writer_matches_add_step():
    """The Anakin engine's in-scan writer (``make_scan_writer``) must produce the
    EXACT ring + stamp planes the host-side donated ``add_step`` scatter does —
    including wrap-around — so ``make_sample_gather`` and ``Health/replay_age_*``
    behave identically whichever path fed the ring."""
    n_envs, cap, steps = 3, 8, 13  # wraps
    rng = np.random.default_rng(5)
    rows = [_transition_row(rng, n_envs, t) for t in range(steps)]

    host = DeviceTransitionRing(cap, n_envs, _transition_specs())
    for t, row in enumerate(rows):
        host.add_step(row, t % cap, t)

    scanned = DeviceTransitionRing(cap, n_envs, _transition_specs())
    write = scanned.make_scan_writer()

    @jax.jit
    def run(arrays, stacked):
        def step(arrays, x):
            row, t = x
            return write(arrays, row, t), None

        arrays, _ = jax.lax.scan(step, arrays, (stacked, jnp.arange(steps, dtype=jnp.int32)))
        return arrays

    stacked = {k: jnp.asarray(np.concatenate([r[k] for r in rows], 0)) for k in rows[0]}
    scanned.arrays = run(scanned.arrays, stacked)
    for k in ("obs", "next_obs", "actions", "rewards", "dones", STAMP_KEY):
        np.testing.assert_array_equal(scanned.host_rows(k), host.host_rows(k), err_msg=k)
