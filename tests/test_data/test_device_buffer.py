"""Device-resident replay mirror (``data/device_buffer.py``): the scatter/gather
round trip must reproduce exactly what the host buffer would have sampled."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import DeviceReplayMirror, gather_sequences


def _row(rng, n_envs, t):
    return {
        "rgb": rng.integers(0, 255, (1, n_envs, 3, 8, 8), dtype=np.uint8),
        "rewards": np.full((1, n_envs, 1), float(t), np.float32),
        "is_first": np.zeros((1, n_envs, 1), np.float32),
    }


def _specs():
    return {"rgb": ((3, 8, 8), jnp.uint8), "rewards": ((1,), jnp.float32), "is_first": ((1,), jnp.float32)}


def test_mirror_matches_host_rows():
    n_envs, cap, seq = 3, 16, 4
    rng = np.random.default_rng(0)
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    rb.seed(0)
    mirror = DeviceReplayMirror(cap, n_envs, _specs())

    for t in range(25):  # wraps the ring
        row = _row(rng, n_envs, t)
        positions = [rb.buffer[e]._pos for e in range(n_envs)]
        mirror.add(row, list(range(n_envs)), positions)
        rb.add(row)
        if t % 7 == 3:  # uneven terminal adds: per-env cursors diverge
            sub = {k: v[:, :1] for k, v in _row(rng, n_envs, 100 + t).items()}
            mirror.add(sub, [1], [rb.buffer[1]._pos])
            rb.add(sub, indices=[1])

    # Every mirror row must equal the host row at the same (pos, env).
    for k in ("rgb", "rewards"):
        dev = np.asarray(jax.device_get(mirror.arrays[k]))
        for e in range(n_envs):
            host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *dev.shape[2:])
            np.testing.assert_array_equal(dev[:, e], host, err_msg=f"{k} env {e}")

    # Index-sampled device gather == host rows at those indices.
    envs, starts = rb.sample_idx(8, seq)
    out = jax.jit(lambda m, e, s: gather_sequences(m, e, s, seq))(
        mirror.arrays, jnp.asarray(envs, jnp.int32), jnp.asarray(starts, jnp.int32)
    )
    for b in range(8):
        e, st = int(envs[b]), int(starts[b])
        host = np.asarray(rb.buffer[e]._buf["rewards"])[:, 0]
        expect = np.stack([host[(st + t) % cap] for t in range(seq)])
        np.testing.assert_array_equal(np.asarray(out["rewards"])[:, b], expect)


def test_mirror_load_from_resume():
    n_envs, cap = 2, 8
    rng = np.random.default_rng(1)
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    for t in range(5):
        rb.add(_row(rng, n_envs, t))
    mirror = DeviceReplayMirror(cap, n_envs, _specs())
    mirror.load_from(rb)
    dev = np.asarray(jax.device_get(mirror.arrays["rewards"]))
    for e in range(n_envs):
        np.testing.assert_array_equal(dev[:5, e, 0], np.arange(5, dtype=np.float32))
