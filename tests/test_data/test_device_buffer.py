"""Device-resident replay mirror (``data/device_buffer.py``): the scatter/gather
round trip must reproduce exactly what the host buffer would have sampled."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import DeviceReplayMirror


def _row(rng, n_envs, t):
    return {
        "rgb": rng.integers(0, 255, (1, n_envs, 3, 8, 8), dtype=np.uint8),
        "rewards": np.full((1, n_envs, 1), float(t), np.float32),
        "is_first": np.zeros((1, n_envs, 1), np.float32),
    }


def _specs():
    return {"rgb": ((3, 8, 8), jnp.uint8), "rewards": ((1,), jnp.float32), "is_first": ((1,), jnp.float32)}


def test_mirror_matches_host_rows():
    n_envs, cap, seq = 3, 16, 4
    rng = np.random.default_rng(0)
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    rb.seed(0)
    mirror = DeviceReplayMirror(cap, n_envs, _specs())

    for t in range(25):  # wraps the ring
        row = _row(rng, n_envs, t)
        positions = [rb.buffer[e]._pos for e in range(n_envs)]
        mirror.add(row, list(range(n_envs)), positions)
        rb.add(row)
        if t % 7 == 3:  # uneven terminal adds: per-env cursors diverge
            sub = {k: v[:, :1] for k, v in _row(rng, n_envs, 100 + t).items()}
            mirror.add(sub, [1], [rb.buffer[1]._pos])
            rb.add(sub, indices=[1])

    # Every mirror row must equal the host row at the same (pos, env).
    for k in ("rgb", "rewards"):
        dev = mirror.host_rows(k)
        for e in range(n_envs):
            host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *dev.shape[2:])
            np.testing.assert_array_equal(dev[:, e], host, err_msg=f"{k} env {e}")

    # Index-sampled device gather == host rows at those indices.
    envs, starts = rb.sample_idx(8, seq)
    out = jax.jit(mirror.make_gather_fn(seq))(
        mirror.arrays, jnp.asarray(envs, jnp.int32), jnp.asarray(starts, jnp.int32)
    )
    for b in range(8):
        e, st = int(envs[b]), int(starts[b])
        host = np.asarray(rb.buffer[e]._buf["rewards"])[:, 0]
        expect = np.stack([host[(st + t) % cap] for t in range(seq)])
        np.testing.assert_array_equal(np.asarray(out["rewards"])[:, b], expect)


def test_sharded_mirror_parity_with_host():
    """dp>1 (env axis sharded over the CPU mesh's data axis): scatter, per-shard
    index sampling, and the shard_map gather must reproduce exactly what the host
    buffer would sample — the device path ≡ host path contract under DP."""
    from sheeprl_tpu.data.device_buffer import sample_index_block
    from sheeprl_tpu.parallel.mesh import build_mesh

    dp, n_envs, cap, seq, batch = 4, 8, 16, 4, 8
    mesh = build_mesh(data=dp, devices=jax.devices()[:dp])
    rng = np.random.default_rng(2)
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    rb.seed(2)
    mirror = DeviceReplayMirror(cap, n_envs, _specs(), mesh=mesh, dp=dp)

    for t in range(25):  # wraps the ring
        row = _row(rng, n_envs, t)
        positions = [rb.buffer[e]._pos for e in range(n_envs)]
        mirror.add(row, list(range(n_envs)), positions)
        rb.add(row)
        if t % 7 == 3:  # subset writes with per-env cursors diverging
            sub = {k: v[:, :1] for k, v in _row(rng, n_envs, 100 + t).items()}
            mirror.add(sub, [5], [rb.buffer[5]._pos])
            rb.add(sub, indices=[5])

    for k in ("rgb", "rewards"):
        dev = mirror.host_rows(k)
        for e in range(n_envs):
            host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *dev.shape[2:])
            np.testing.assert_array_equal(dev[:, e], host, err_msg=f"{k} env {e}")

    # Per-shard sampling keeps batch element j on the shard owning env j's block...
    envs, starts = sample_index_block(rb, batch, seq, n=3, dp=dp)
    e_local, b_local = n_envs // dp, batch // dp
    for g in range(3):
        for j in range(batch):
            assert envs[g, j] // e_local == j // b_local

    # Resume path under dp>1: a freshly-built sharded mirror rebuilt from the host
    # buffer must hold the same rows (and keep the env sharding).
    rebuilt = DeviceReplayMirror(cap, n_envs, _specs(), mesh=mesh, dp=dp)
    rebuilt.load_from(rb)
    for k in ("rgb", "rewards"):
        np.testing.assert_array_equal(rebuilt.host_rows(k), mirror.host_rows(k), err_msg=f"load_from {k}")
        assert rebuilt.arrays[k].sharding.spec == jax.sharding.PartitionSpec("data")

    # ...so the shard_map gather is shard-local and matches the host rows.
    gather = jax.jit(mirror.make_gather_fn(seq))
    out = gather(mirror.arrays, jnp.asarray(envs[0], jnp.int32), jnp.asarray(starts[0], jnp.int32))
    assert out["rewards"].sharding.spec == jax.sharding.PartitionSpec(None, "data")
    for b in range(batch):
        e, st = int(envs[0][b]), int(starts[0][b])
        for k in ("rgb", "rewards"):
            host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *np.asarray(out[k]).shape[2:])
            expect = np.stack([host[(st + t) % cap] for t in range(seq)])
            np.testing.assert_array_equal(np.asarray(out[k])[:, b], expect, err_msg=f"{k} b={b}")


def test_mirror_load_from_resume():
    n_envs, cap = 2, 8
    rng = np.random.default_rng(1)
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    for t in range(5):
        rb.add(_row(rng, n_envs, t))
    mirror = DeviceReplayMirror(cap, n_envs, _specs())
    mirror.load_from(rb)
    dev = mirror.host_rows("rewards")
    for e in range(n_envs):
        np.testing.assert_array_equal(dev[:5, e, 0], np.arange(5, dtype=np.float32))
