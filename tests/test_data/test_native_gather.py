"""Native C++ replay gather vs the numpy reference path (sheeprl_tpu/native)."""

import numpy as np
import pytest

from sheeprl_tpu import native
from sheeprl_tpu.data.buffers import ReplayBuffer, SequentialReplayBuffer


@pytest.fixture(scope="module")
def lib_available():
    if native.load() is None:
        pytest.skip("native gather library unavailable (no toolchain?)")


def test_gather_seq_matches_numpy(lib_available):
    rng = np.random.default_rng(0)
    size, n_envs, feat = 64, 3, (5, 4)
    src = rng.integers(0, 255, (size, n_envs) + feat, dtype=np.uint8)
    n_samples, T, B = 2, 7, 4
    starts = rng.integers(0, size, n_samples * B).astype(np.int64)
    envs = rng.integers(0, n_envs, n_samples * B).astype(np.int64)

    out = native.gather_seq(src, starts, envs, n_samples, T, B)
    assert out is not None
    assert out.shape == (n_samples, T, B) + feat
    for s in range(n_samples):
        for b in range(B):
            for t in range(T):
                row = (starts[s * B + b] + t) % size
                np.testing.assert_array_equal(out[s, t, b], src[row, envs[s * B + b]])

    # start_offset shifts the whole window (used for next-obs gathers)
    out1 = native.gather_seq(src, starts, envs, n_samples, T, B, start_offset=1)
    np.testing.assert_array_equal(out1[0, 0, 0], src[(starts[0] + 1) % size, envs[0]])


def test_gather_rows_matches_numpy(lib_available):
    rng = np.random.default_rng(1)
    src = rng.standard_normal((50, 2, 6)).astype(np.float32)
    rows = rng.integers(0, 50, 33).astype(np.int64)
    envs = rng.integers(0, 2, 33).astype(np.int64)
    out = native.gather_rows(src, rows, envs)
    assert out is not None
    np.testing.assert_array_equal(out, src[rows, envs])


def test_sequential_buffer_native_vs_fallback(lib_available, monkeypatch):
    """The full SequentialReplayBuffer.sample must produce identical results with the
    native gather and the numpy fallback (same rng stream → same indices)."""
    def fill(rb):
        rng = np.random.default_rng(2)
        for step in range(90):  # > buffer size: exercises wraparound starts
            rb.add({
                "obs": rng.integers(0, 255, (1, 2, 3, 8, 8), dtype=np.uint8).astype(np.float32),
                "rewards": rng.standard_normal((1, 2, 1)).astype(np.float32),
            })

    rb_native = SequentialReplayBuffer(64, 2)
    fill(rb_native)
    rb_native.seed(7)
    native_out = rb_native.sample(batch_size=5, n_samples=3, sequence_length=9)

    rb_np = SequentialReplayBuffer(64, 2)
    fill(rb_np)
    rb_np.seed(7)
    monkeypatch.setattr(native, "gather_seq", lambda *a, **k: None)
    np_out = rb_np.sample(batch_size=5, n_samples=3, sequence_length=9)

    assert set(native_out) == set(np_out)
    for k in np_out:
        np.testing.assert_array_equal(native_out[k], np_out[k], err_msg=k)


def test_replay_buffer_native_vs_fallback(lib_available, monkeypatch):
    def fill(rb):
        rng = np.random.default_rng(3)
        for _ in range(40):
            rb.add({
                "obs": rng.standard_normal((1, 2, 4)).astype(np.float32),
                "rewards": rng.standard_normal((1, 2, 1)).astype(np.float32),
            })

    rb_native = ReplayBuffer(32, 2, obs_keys=("obs",))
    fill(rb_native)
    rb_native.seed(11)
    a = rb_native.sample(batch_size=8, n_samples=2, sample_next_obs=True)

    rb_np = ReplayBuffer(32, 2, obs_keys=("obs",))
    fill(rb_np)
    rb_np.seed(11)
    monkeypatch.setattr(native, "gather_rows", lambda *a, **k: None)
    b = rb_np.sample(batch_size=8, n_samples=2, sample_next_obs=True)

    assert set(a) == set(b)
    for k in b:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)
