"""Tier-1 smoke of benchmarks/replay_bench.py: tiny-shape invocation of all three
replay data paths (host-per-step / host-block / device-ring fused), JSON rows
compatible with the BENCH_*.json trajectory."""

from __future__ import annotations

import json
import os
import sys


def _load_bench_module():
    bench_dir = os.path.join(os.path.dirname(__file__), "..", "..", "benchmarks")
    sys.path.insert(0, os.path.abspath(bench_dir))
    try:
        import replay_bench
    finally:
        sys.path.pop(0)
    return replay_bench


def test_replay_bench_smoke(capsys, tmp_path):
    replay_bench = _load_bench_module()
    out_path = tmp_path / "replay_bench.json"
    rates = replay_bench.main(
        [
            "--batch", "8",
            "--hidden", "8",
            "--blocks", "2",
            "--utd", "3",
            "--algos", "sac,droq",
            "--json-out", str(out_path),
        ]
    )
    assert set(rates) == {"sac", "droq"}
    for algo in ("sac", "droq"):
        assert set(rates[algo]) == {"host_per_step", "host_block", "device_ring"}
        assert all(v > 0 for v in rates[algo].values())

    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip().startswith("{")]
    rows = [json.loads(ln) for ln in lines]
    metrics = {r["metric"] for r in rows}
    for algo in ("sac", "droq"):
        assert f"{algo}_replay_device_ring_grad_steps_per_sec" in metrics
        assert f"{algo}_replay_device_ring_speedup_vs_per_step" in metrics
    for r in rows:
        assert {"metric", "value", "unit"} <= set(r)
        assert isinstance(r["value"], (int, float))

    saved = json.loads(out_path.read_text())
    assert [r["metric"] for r in saved] == [r["metric"] for r in rows]
