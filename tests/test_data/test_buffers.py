"""ReplayBuffer semantics (modeled on the reference suite ``tests/test_data/test_buffers.py``)."""

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, ReplayBuffer, SequentialReplayBuffer


def _data(t, n_envs, pos0=0):
    return {
        "observations": np.arange(pos0, pos0 + t, dtype=np.float32).reshape(t, 1, 1).repeat(n_envs, 1),
        "dones": np.zeros((t, n_envs, 1), dtype=np.float32),
    }


def test_replay_buffer_add_and_len():
    rb = ReplayBuffer(8, n_envs=2)
    rb.add(_data(3, 2))
    assert len(rb) == 3
    assert not rb.full
    rb.add(_data(5, 2, 3))
    assert len(rb) == 8
    assert rb.full


def test_replay_buffer_wraparound():
    rb = ReplayBuffer(4, n_envs=1)
    rb.add(_data(3, 1))
    rb.add(_data(3, 1, 3))
    assert rb.full
    # Positions 0,1 hold steps 4,5 (wrapped); 2,3 hold 2,3.
    assert rb["observations"][0, 0, 0] == 4.0
    assert rb["observations"][1, 0, 0] == 5.0
    assert rb["observations"][2, 0, 0] == 2.0


def test_replay_buffer_oversized_add():
    rb = ReplayBuffer(4, n_envs=1)
    rb.add(_data(10, 1))
    assert rb.full
    # Only the trailing window survives.
    assert sorted(rb["observations"][:, 0, 0].tolist()) == [6.0, 7.0, 8.0, 9.0]


def test_replay_buffer_sample_shapes():
    rb = ReplayBuffer(16, n_envs=2)
    rb.add(_data(10, 2))
    s = rb.sample(6, n_samples=3)
    assert s["observations"].shape == (3, 6, 1)


def test_replay_buffer_sample_next_obs_pairs():
    rb = ReplayBuffer(8, n_envs=1)
    rb.add(_data(8, 1))
    s = rb.sample(64, sample_next_obs=True)
    obs, nxt = s["observations"][0, :, 0], s["next_observations"][0, :, 0]
    assert np.allclose(nxt, obs + 1)


def test_replay_buffer_sample_next_obs_full_no_cursor_crossing():
    rb = ReplayBuffer(6, n_envs=1)
    rb.add(_data(9, 1))  # full, pos=3; entries 3..8 with oldest (3) at index 3
    s = rb.sample(256, sample_next_obs=True)
    obs, nxt = s["observations"][0, :, 0], s["next_observations"][0, :, 0]
    assert np.allclose(nxt, obs + 1)  # never pairs newest with oldest


def test_replay_buffer_sample_errors():
    rb = ReplayBuffer(4)
    with pytest.raises(ValueError):
        rb.sample(1)
    rb.add(_data(2, 1))
    with pytest.raises(ValueError):
        rb.sample(0)


def test_replay_buffer_getitem_setitem():
    rb = ReplayBuffer(4, n_envs=2)
    rb["rewards"] = np.ones((4, 2, 1), dtype=np.float32)
    assert rb["rewards"].sum() == 8
    with pytest.raises(RuntimeError):
        rb["bad"] = np.ones((3, 2, 1))


def test_replay_buffer_memmap(tmp_path):
    rb = ReplayBuffer(8, n_envs=1, memmap=True, memmap_dir=tmp_path / "mm")
    rb.add(_data(4, 1))
    assert rb.is_memmap
    assert (tmp_path / "mm" / "observations.memmap").exists()
    assert len(rb) == 4


def test_replay_buffer_state_dict_roundtrip():
    rb = ReplayBuffer(8, n_envs=1)
    rb.add(_data(5, 1))
    state = rb.state_dict()
    rb2 = ReplayBuffer(8, n_envs=1)
    rb2.load_state_dict(state)
    assert len(rb2) == 5
    assert np.allclose(rb2["observations"], rb["observations"])


# -- SequentialReplayBuffer -------------------------------------------------


def test_sequential_sample_contiguous():
    rb = SequentialReplayBuffer(32, n_envs=1)
    rb.add(_data(20, 1))
    s = rb.sample(4, sequence_length=5, n_samples=2)
    assert s["observations"].shape == (2, 5, 4, 1)
    seq = s["observations"][0, :, 0, 0]
    assert np.allclose(np.diff(seq), 1)


def test_sequential_sample_full_wraparound_valid():
    rb = SequentialReplayBuffer(8, n_envs=1)
    rb.add(_data(12, 1))  # full, pos=4, valid chronological window 4..11
    s = rb.sample(64, sequence_length=3)
    seqs = s["observations"][0]  # [T, B, 1]
    diffs = np.diff(seqs[:, :, 0], axis=0)
    assert np.allclose(diffs, 1)  # every sequence strictly consecutive


def test_sequential_sample_too_long_raises():
    rb = SequentialReplayBuffer(8, n_envs=1)
    rb.add(_data(4, 1))
    with pytest.raises(ValueError):
        rb.sample(1, sequence_length=6)


def test_sample_transition_idx_matches_sample_validity():
    """Index-only transition sampling (the SAC-AE device mirror's sampler) draws
    only filled rows / valid envs, both before and after the ring wraps."""
    rb = ReplayBuffer(8, n_envs=3)
    rb.seed(0)
    rb.add(_data(5, 3))
    idxs, envs = rb.sample_transition_idx(16, n_samples=2)
    assert idxs.shape == envs.shape == (2, 16)
    assert idxs.max() < 5 and idxs.min() >= 0  # only the 5 filled rows
    assert envs.max() < 3 and envs.min() >= 0
    rb.add(_data(6, 3, pos0=5))  # wraps: full buffer, every row valid
    idxs, _ = rb.sample_transition_idx(64)
    assert idxs.max() < 8
    empty = ReplayBuffer(8, n_envs=1)
    with pytest.raises(ValueError):
        empty.sample_transition_idx(4)


# -- EnvIndependentReplayBuffer ---------------------------------------------


def test_env_independent_add_indices_and_sample():
    rb = EnvIndependentReplayBuffer(16, n_envs=3)
    data = _data(4, 2)
    rb.add(data, indices=[0, 2])
    assert len(rb.buffer[0]) == 4
    assert len(rb.buffer[1]) == 0
    assert len(rb.buffer[2]) == 4
    s = rb.sample(8)
    assert s["observations"].shape[:2] == (1, 8)


def test_env_independent_sequential():
    rb = EnvIndependentReplayBuffer(32, n_envs=2, buffer_cls=SequentialReplayBuffer)
    rb.add(_data(16, 2))
    s = rb.sample(4, sequence_length=4)
    assert s["observations"].shape == (1, 4, 4, 1)
