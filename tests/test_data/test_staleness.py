"""Replay-sample-age (staleness) stats recorded by the buffers at sampling time."""

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, ReplayBuffer, SequentialReplayBuffer


def _rows(t, n_envs=1, dim=2, base=0):
    return {"obs": np.arange(base, base + t * n_envs * dim, dtype=np.float32).reshape(t, n_envs, dim)}


def test_no_metrics_before_first_sample():
    rb = ReplayBuffer(8, 1, obs_keys=("obs",))
    rb.add(_rows(4))
    assert rb.sample_age_metrics() == {}


def test_ages_bounded_by_buffer_content():
    rb = ReplayBuffer(16, 1, obs_keys=("obs",))
    rb.seed(0)
    rb.add(_rows(10))
    rb.sample(64)
    ages = rb.sample_age_metrics()
    assert set(ages) == {"Health/replay_age_mean", "Health/replay_age_max"}
    # 10 rows added: the freshest row has age 0, the oldest age 9.
    assert 0 <= ages["Health/replay_age_mean"] <= 9
    assert ages["Health/replay_age_max"] <= 9


def test_ages_grow_as_the_ring_rotates():
    rb = ReplayBuffer(8, 1, obs_keys=("obs",))
    rb.seed(0)
    rb.add(_rows(8))
    rb.sample(32)
    first_max = rb.sample_age_metrics()["Health/replay_age_max"]
    # 100 more adds: the ring still holds only the newest 8 rows, so ages stay < 8.
    for i in range(100):
        rb.add(_rows(1, base=i))
    rb.sample(32)
    ages = rb.sample_age_metrics()
    assert ages["Health/replay_age_max"] <= 7
    assert first_max <= 7


def test_index_only_sampling_records_ages():
    rb = ReplayBuffer(16, 2, obs_keys=("obs",))
    rb.seed(0)
    rb.add(_rows(12, n_envs=2))
    rb.sample_transition_idx(8)
    assert rb.sample_age_metrics()["Health/replay_age_max"] <= 11


def test_sequential_buffer_ages_from_sequence_starts():
    rb = SequentialReplayBuffer(32, 1, obs_keys=("obs",))
    rb.seed(0)
    rb.add(_rows(20))
    rb.sample(4, sequence_length=5)
    ages = rb.sample_age_metrics()
    # A sequence start can be at most seq_len-1 from the end: age <= 19.
    assert 0 <= ages["Health/replay_age_mean"] <= 19


def test_env_independent_aggregation():
    rb = EnvIndependentReplayBuffer(16, n_envs=2, obs_keys=("obs",), buffer_cls=SequentialReplayBuffer)
    rb.seed(0)
    assert rb.sample_age_metrics() == {}
    rb.add(_rows(10, n_envs=2))
    rb.sample_idx(8, sequence_length=4)
    ages = rb.sample_age_metrics()
    assert set(ages) == {"Health/replay_age_mean", "Health/replay_age_max"}
    assert ages["Health/replay_age_max"] <= 9


def test_ages_survive_checkpoint_roundtrip():
    rb = ReplayBuffer(8, 1, obs_keys=("obs",))
    rb.seed(0)
    rb.add(_rows(6))
    state = rb.state_dict()
    restored = ReplayBuffer(8, 1, obs_keys=("obs",))
    restored.seed(0)
    restored.load_state_dict(state)
    restored.sample(16)
    ages = restored.sample_age_metrics()
    # Approximate stamps rebuilt from ring order: ages stay within the held rows.
    assert 0 <= ages["Health/replay_age_max"] <= 5


def test_overfill_add_stamps_trailing_window():
    rb = ReplayBuffer(4, 1, obs_keys=("obs",))
    rb.seed(0)
    rb.add(_rows(10))  # only the trailing 4 rows survive
    rb.sample(16)
    assert rb.sample_age_metrics()["Health/replay_age_max"] <= 3
