"""AsyncBatchPrefetcher unit tests."""

import numpy as np

from sheeprl_tpu.data.prefetch import AsyncBatchPrefetcher


def test_prefetcher_matches_synchronous_sampler():
    calls = []

    def sample(n):
        calls.append(n)
        return np.full((n, 2), len(calls))

    pf = AsyncBatchPrefetcher(sample)
    a = pf.get(3)  # no staged block: synchronous
    assert a.shape == (3, 2)
    b = pf.get(3)  # staged block from the background request
    assert b.shape == (3, 2)
    c = pf.get(5)  # size change: staged block drained, fresh synchronous sample
    assert c.shape == (5, 2)
    pf.close()


def test_prefetcher_propagates_worker_exceptions():
    state = {"fail": False}

    def sample(n):
        if state["fail"]:
            raise RuntimeError("boom")
        return np.zeros((n, 1))

    pf = AsyncBatchPrefetcher(sample)
    pf.get(2)
    state["fail"] = True  # the staged request for the NEXT get(2) will fail
    try:
        pf.get(2)
        raised = False
    except RuntimeError:
        raised = True
    assert raised
    state["fail"] = False
    pf.close()


def test_prefetcher_lock_guards_buffer_writes():
    import threading
    import time as _time

    writes = []

    def sample(n):
        _time.sleep(0.05)
        return list(writes)

    pf = AsyncBatchPrefetcher(sample)
    pf.get(1)  # stages a background sample holding the lock for 50ms
    with pf.lock:
        writes.append(1)
    assert pf.get(1) is not None
    pf.close()


def test_prefetcher_list_block_slice_reuse():
    """Per-step list blocks must be reused by LIST slicing, not leaf slicing."""
    calls = []

    def sample(n):
        calls.append(n)
        return [np.full((4, 2), g) for g in range(n)]

    pf = AsyncBatchPrefetcher(sample)
    pf.get(3)          # stages a 3-step block
    block = pf.get(2)  # smaller request: first 2 staged steps, arrays intact
    assert len(block) == 2 and block[0].shape == (4, 2)
    pf.close()
