"""EnvPool worker-budget sharding across co-located Sebulba actors."""

import pytest

from sheeprl_tpu.rollout.sharding import shard_worker_count


def test_single_actor_passthrough():
    assert shard_worker_count(8, 1, 0) == 8
    assert shard_worker_count(None, 1, 0) is None


def test_even_split():
    assert [shard_worker_count(8, 2, i) for i in range(2)] == [4, 4]


def test_remainder_to_lowest_ids():
    shards = [shard_worker_count(8, 3, i) for i in range(3)]
    assert shards == [3, 3, 2]
    assert sum(shards) == 8


def test_floor_of_one():
    assert [shard_worker_count(2, 4, i) for i in range(4)] == [1, 1, 1, 1]


def test_default_budget_shards_cpu_count():
    shards = [shard_worker_count(None, 2, i) for i in range(2)]
    assert all(isinstance(s, int) and s >= 1 for s in shards)


def test_actor_id_out_of_range():
    with pytest.raises(ValueError):
        shard_worker_count(8, 2, 2)
