"""Weight-publish contract: freshest-wins eviction, stamps/staleness, and the
device-vs-host transfer discipline (ISSUE 13 acceptance: no per-publish
``device_get`` on the device path, asserted via ``jax.transfer_guard``)."""

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.distributed.publish import (
    DeviceWeightPublisher,
    ChannelWeightPublisher,
    evict_and_put,
    make_stamp,
    staleness_steps,
)
from sheeprl_tpu.distributed.transport import Listener, connect, tree_digest


def test_evict_and_put_freshest_wins():
    q = queue.Queue(maxsize=1)
    assert evict_and_put(q, "v1") == 0
    assert evict_and_put(q, "v2") == 1  # v1 evicted, not blocked behind
    assert evict_and_put(q, "v3") == 1
    assert q.get_nowait() == "v3"
    assert q.empty()


def test_evict_and_put_deeper_queue():
    q = queue.Queue(maxsize=2)
    assert evict_and_put(q, 1) == 0
    assert evict_and_put(q, 2) == 0
    assert evict_and_put(q, 3) == 1
    assert [q.get_nowait(), q.get_nowait()] == [2, 3]


def test_staleness_steps():
    assert staleness_steps(None, 100) is None
    assert staleness_steps({}, 100) is None
    assert staleness_steps(make_stamp(1, 5, 80), 100) == 20
    assert staleness_steps(make_stamp(1, 5, 100), 100) == 0
    # Clock skew between producer/consumer counters never goes negative.
    assert staleness_steps(make_stamp(1, 5, 120), 100) == 0


def test_device_publisher_no_host_roundtrip():
    """The device path performs NO device-to-host transfer per publish: with
    device_to_host transfers disallowed, publishes still succeed (a device_get
    would raise)."""
    params = {"w": jnp.ones((8, 8)), "b": jnp.zeros((8,))}
    q = queue.Queue(maxsize=1)
    pub = DeviceWeightPublisher(lambda item: evict_and_put(q, item), device=jax.devices()[0])
    with jax.transfer_guard_device_to_host("disallow"):
        for step in range(3):
            stamp = pub.publish(params, grad_step=step, policy_step=step * 4)
    assert stamp == make_stamp(3, 2, 8)
    placed, got_stamp = q.get_nowait()  # freshest-wins: only the last publish
    assert got_stamp["seq"] == 3
    assert isinstance(placed["w"], jax.Array)
    assert pub.bytes_published > 0
    # The published leaves are real device arrays the consumer can use directly.
    np.testing.assert_array_equal(np.asarray(placed["w"]), np.ones((8, 8)))


def test_channel_publisher_host_fallback_and_welcome():
    """The cross-process fallback does ONE device_get per publish, fans the same
    host copy to every channel, and replays the latest to a late joiner."""
    params = {"w": jnp.full((4, 4), 2.0), "b": jnp.arange(4, dtype=jnp.float32)}
    lis = Listener()
    learner_side = []

    def accept_one():
        learner_side.append(lis.accept(5.0))

    t = threading.Thread(target=accept_one)
    t.start()
    actor = connect("127.0.0.1", lis.port, timeout_s=5.0)
    t.join()

    pub = ChannelWeightPublisher(lambda: list(learner_side))
    pub.publish(params, grad_step=1, policy_step=4)
    pub.publish(params, grad_step=2, policy_step=8)
    kinds = []
    for _ in range(2):
        kind, meta, payload = actor.recv(timeout=5.0)
        kinds.append(kind)
    assert kinds == ["params", "params"]
    assert meta["stamp"] == make_stamp(2, 2, 8)
    assert tree_digest(payload) == tree_digest(jax.device_get(params))

    # Welcome: a channel that joins after publishes still gets the freshest stamp.
    t2 = threading.Thread(target=accept_one)
    t2.start()
    late = connect("127.0.0.1", lis.port, timeout_s=5.0)
    t2.join()
    pub.maybe_welcome(learner_side[1])
    kind, meta, payload = late.recv(timeout=5.0)
    assert kind == "params" and meta["stamp"]["seq"] == 2
    assert tree_digest(payload) == tree_digest(jax.device_get(params))

    for ch in learner_side + [actor, late]:
        ch.close()
    lis.close()


def test_channel_publisher_welcome_noop_before_first_publish():
    pub = ChannelWeightPublisher(lambda: [])

    class Boom:
        def send(self, *a, **k):  # would blow up if welcome sent anything
            raise AssertionError("welcome must be a no-op before the first publish")

    pub.maybe_welcome(Boom())


def test_channel_publisher_survives_dead_channel():
    lis = Listener()
    chans = []
    t = threading.Thread(target=lambda: chans.append(lis.accept(5.0)))
    t.start()
    actor = connect("127.0.0.1", lis.port, timeout_s=5.0)
    t.join()
    actor.close()  # peer died before the publish
    pub = ChannelWeightPublisher(lambda: list(chans))
    params = {"w": jnp.ones((64, 64))}
    for _ in range(50):  # outlast socket buffering; must never raise
        pub.publish(params, grad_step=1, policy_step=1)
    assert pub.seq == 50
    chans[0].close()
    lis.close()


# ------------------------------------------- concurrency contract (jaxlint JL010)
def test_channel_publisher_concurrent_publish_and_welcome():
    """The publisher's ``device_get`` and socket sends happen OUTSIDE its lock
    (JL010 fix): racing publishes and welcomes must still hand every consumer a
    monotonically-applicable stream — the consumer's max-seq guard keeps the
    freshest params even when an older welcome overtakes a newer broadcast."""
    from sheeprl_tpu.distributed.sebulba import _pickup_params

    lis = Listener()
    learner_side = []

    def accept_one():
        learner_side.append(lis.accept(5.0))

    t = threading.Thread(target=accept_one)
    t.start()
    actor = connect("127.0.0.1", lis.port, timeout_s=5.0)
    t.join()

    pub = ChannelWeightPublisher(lambda: list(learner_side))
    params = {"w": jnp.ones((8, 8))}
    n_threads, n_each = 4, 5
    errors = []

    def spam(i):
        try:
            for _ in range(n_each):
                pub.publish(params, grad_step=i, policy_step=i)
                pub.maybe_welcome(learner_side[0])
        except Exception as e:  # pragma: no cover - the assertion is no-raise
            errors.append(e)

    threads = [threading.Thread(target=spam, args=(i,)) for i in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    assert errors == []
    assert pub.seq == n_threads * n_each  # no lost seq increments
    assert pub.bytes_published > 0

    # consumer side: drain everything; the max-seq guard must settle on the
    # globally freshest publish regardless of wire arrival order
    import time as _time

    deadline = _time.monotonic() + 5.0
    latest = None
    while _time.monotonic() < deadline:
        latest = _pickup_params(actor, latest)
        if latest is not None and int(latest[1]["seq"]) == pub.seq:
            break
        _time.sleep(0.01)
    assert latest is not None
    assert int(latest[1]["seq"]) == pub.seq

    actor.close()
    for ch in learner_side:
        ch.close()
    lis.close()


def test_freshest_prefers_max_seq_not_last_arrived():
    from sheeprl_tpu.distributed.sebulba import _freshest

    newer = ("p2", {"seq": 7})
    older = ("p1", {"seq": 3})
    assert _freshest(None, older) is older
    assert _freshest(older, newer) is newer
    # the regression: an out-of-order older arrival must NOT replace the newer
    assert _freshest(newer, older) is newer
    # equal seq: the later arrival wins (welcome re-send of the same publish)
    resend = ("p2b", {"seq": 7})
    assert _freshest(newer, resend) is resend
