"""Sebulba end-to-end: real subprocess topologies on localhost (ISSUE 13).

Two pins:

* a 2-process SAC launcher run (learner + 1 actor) completes cleanly and its
  summary shows blocks, gradient steps and transport bytes flowing;
* the 1-actor PPO Sebulba placement feeds the learner BIT-IDENTICAL training
  blocks to the in-process thread-decoupled path on the same seed (the
  ``SHEEPRL_TPU_BATCH_DIGEST`` hook hashes every consumed block in both).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

# Every test spawns JAX subprocesses that recompile everything — slow tier.
pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[2]

SAC_OVERRIDES = [
    "exp=sac_decoupled",
    "env=continuous_dummy",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=8",
    "algo.per_rank_batch_size=8",
    "algo.learning_starts=4",
    "algo.total_steps=16",
    "buffer.size=256",
    "dry_run=False",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "algo.run_test=False",
    "checkpoint.every=8",
    "checkpoint.save_last=True",
    "metric.log_every=4",
    "buffer.memmap=False",
]

PPO_OVERRIDES = [
    "exp=ppo_decoupled",
    "env=discrete_dummy",
    "algo.mlp_keys.encoder=[state]",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=8",
    "algo.update_epochs=1",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.total_steps=64",
    "dry_run=False",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "algo.run_test=False",
    "checkpoint.every=32",
    "checkpoint.save_last=True",
    "metric.log_every=16",
    "buffer.memmap=False",
]


def _child_env(**extra):
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        SHEEPRL_TPU_QUIET="1",
    )
    env.update({k: str(v) for k, v in extra.items()})
    return env

def _run(module, overrides, env, timeout):
    proc = subprocess.run(
        [sys.executable, "-m", module, *overrides],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, f"{module} failed rc={proc.returncode}:\n{proc.stdout[-4000:]}"
    return proc.stdout


def test_sebulba_sac_launcher_two_process_smoke(tmp_path):
    """Launcher spawns learner + 1 actor as REAL processes; the run finishes,
    writes checkpoints, and the learner summary accounts for every block."""
    summary_path = tmp_path / "summary.json"
    _run(
        "sheeprl_tpu.sebulba",
        SAC_OVERRIDES
        + [
            f"log_root={tmp_path}/logs",
            "distributed.num_actors=1",
            "distributed.connect_timeout_s=30",
        ],
        _child_env(SHEEPRL_TPU_SEBULBA_SUMMARY=summary_path),
        timeout=420,
    )
    summary = json.loads(summary_path.read_text())
    # 16 total steps / 2 envs = 8 actor iterations, every one shipped as a block.
    assert summary["blocks"] == 8
    assert summary["env_steps_total"] == 16
    assert summary["cumulative_grad_steps"] > 0
    assert summary["bytes_received"] > 0 and summary["bytes_published"] > 0
    assert summary["publishes"] > 0
    events = [(e[1], e[2], e[3]) for e in summary["events"]]
    assert (0, 0, "connected") in events and (0, 0, "done") in events
    ckpts = sorted((tmp_path / "logs").rglob("ckpt_*"))
    assert ckpts, "sebulba learner wrote no checkpoint"


def test_sebulba_ppo_one_actor_bit_identical_to_thread_path(tmp_path):
    """The Sebulba process split must be a pure topology change: with 1 actor and
    the same seed, the learner consumes byte-for-byte the same training blocks
    as the thread-decoupled path (transport framing, GAE placement, and the
    lockstep publish cadence all cancel out)."""
    thread_digests = tmp_path / "thread.digest"
    sebulba_digests = tmp_path / "sebulba.digest"

    _run(
        "sheeprl_tpu",
        PPO_OVERRIDES + [f"log_root={tmp_path}/thread_logs"],
        _child_env(SHEEPRL_TPU_BATCH_DIGEST=thread_digests),
        timeout=420,
    )
    _run(
        "sheeprl_tpu.sebulba",
        PPO_OVERRIDES
        + [
            f"log_root={tmp_path}/sebulba_logs",
            "distributed.num_actors=1",
            "distributed.connect_timeout_s=30",
        ],
        _child_env(SHEEPRL_TPU_BATCH_DIGEST=sebulba_digests),
        timeout=420,
    )

    thread_lines = thread_digests.read_text().splitlines()
    sebulba_lines = sebulba_digests.read_text().splitlines()
    assert thread_lines, "thread path recorded no batch digests"
    # 64 total steps / (2 envs * 8 rollout) = 4 updates in both topologies.
    assert len(thread_lines) == 4
    assert sebulba_lines == thread_lines, (
        "sebulba learner consumed different training data than the thread path:\n"
        f"thread : {thread_lines}\nsebulba: {sebulba_lines}"
    )
