"""PlacementSpec composition: config values, env-var precedence, validation."""

import pytest

from sheeprl_tpu.distributed.placement import (
    ACTOR_ID_ENV_VAR,
    GENERATION_ENV_VAR,
    PORT_ENV_VAR,
    ROLE_ENV_VAR,
    PlacementSpec,
    placement_from_cfg,
)


def _cfg(**distributed):
    base = {
        "mode": "sebulba",
        "role": "launcher",
        "num_actors": 1,
        "host": "127.0.0.1",
        "port": 0,
        "actor_id": 0,
        "connect_timeout_s": 60.0,
        "publish": "auto",
        "queue_depth": 2,
        "respawn": True,
        "respawn_backoff_s": 0.5,
        "max_actor_respawns": 3,
    }
    base.update(distributed)
    return {"distributed": base}


def test_defaults_without_distributed_section():
    spec = placement_from_cfg({}, env={})
    assert spec.mode == "thread" and not spec.is_sebulba
    assert spec.role == "launcher" and spec.num_actors == 1


def test_cfg_values_flow_through():
    spec = placement_from_cfg(
        _cfg(role="learner", num_actors=3, port=5001, queue_depth=7), env={}
    )
    assert spec.is_sebulba and spec.is_learner and not spec.is_actor
    assert spec.num_actors == 3 and spec.port == 5001 and spec.queue_depth == 7


def test_env_vars_take_precedence_over_cfg():
    env = {
        ROLE_ENV_VAR: "actor",
        ACTOR_ID_ENV_VAR: "2",
        PORT_ENV_VAR: "6001",
        GENERATION_ENV_VAR: "4",
    }
    spec = placement_from_cfg(_cfg(role="learner", num_actors=3, port=5001), env=env)
    assert spec.is_actor and spec.actor_id == 2
    assert spec.port == 6001 and spec.generation == 4


def test_validation_errors():
    with pytest.raises(ValueError, match="role"):
        PlacementSpec(role="coach")
    with pytest.raises(ValueError, match="publish"):
        PlacementSpec(publish="teleport")
    with pytest.raises(ValueError, match="num_actors"):
        PlacementSpec(num_actors=0)
    with pytest.raises(ValueError, match="actor_id"):
        PlacementSpec(num_actors=2, actor_id=2)
    with pytest.raises(ValueError, match="queue_depth"):
        PlacementSpec(queue_depth=0)


def test_child_overrides():
    spec = PlacementSpec(mode="sebulba", num_actors=2, host="10.0.0.5")
    learner = spec.child_overrides("learner", 7000)
    assert "distributed.role=learner" in learner
    assert "distributed.port=7000" in learner
    assert "distributed.host=10.0.0.5" in learner
    assert "distributed.num_actors=2" in learner
    assert not any(ov.startswith("distributed.actor_id") for ov in learner)
    actor = spec.child_overrides("actor", 7000, actor_id=1)
    assert "distributed.actor_id=1" in actor


def test_composed_config_has_distributed_group():
    from sheeprl_tpu.config.core import compose

    cfg = compose(overrides=["exp=sac_decoupled", "distributed.mode=sebulba", "distributed.num_actors=2"])
    spec = placement_from_cfg(cfg, env={})
    assert spec.is_sebulba and spec.num_actors == 2
