"""``maybe_init_distributed`` env-var rendezvous + init timeout (ISSUE 13 sat. #2)."""

import pytest

import sheeprl_tpu.parallel.mesh as mesh_mod
from sheeprl_tpu.parallel.mesh import (
    BarrierTimeoutError,
    COORDINATOR_ADDRESS_ENV_VAR,
    NUM_PROCESSES_ENV_VAR,
    PROCESS_ID_ENV_VAR,
    maybe_init_distributed,
)


@pytest.fixture(autouse=True)
def _fresh_init_flag(monkeypatch):
    monkeypatch.setattr(mesh_mod, "_distributed_initialized", False)


def _capture(monkeypatch):
    calls = []

    def fake_initialize(coordinator_address=None, num_processes=None, process_id=None):
        calls.append((coordinator_address, num_processes, process_id))

    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", fake_initialize)
    return calls


def test_noop_without_coordinator(monkeypatch):
    calls = _capture(monkeypatch)
    maybe_init_distributed({})
    maybe_init_distributed({"distributed": {}})
    assert calls == []
    assert mesh_mod._distributed_initialized is False


def test_cfg_coordinator_used(monkeypatch):
    calls = _capture(monkeypatch)
    maybe_init_distributed(
        {"distributed": {"coordinator_address": "127.0.0.1:9911", "num_processes": 2, "process_id": 1}}
    )
    assert calls == [("127.0.0.1:9911", 2, 1)]
    assert mesh_mod._distributed_initialized is True


def test_env_var_rendezvous(monkeypatch):
    calls = _capture(monkeypatch)
    monkeypatch.setenv(COORDINATOR_ADDRESS_ENV_VAR, "127.0.0.1:9912")
    monkeypatch.setenv(NUM_PROCESSES_ENV_VAR, "4")
    monkeypatch.setenv(PROCESS_ID_ENV_VAR, "3")
    maybe_init_distributed({"distributed": {}})
    assert calls == [("127.0.0.1:9912", 4, 3)]


def test_cfg_wins_over_env(monkeypatch):
    calls = _capture(monkeypatch)
    monkeypatch.setenv(COORDINATOR_ADDRESS_ENV_VAR, "127.0.0.1:1111")
    monkeypatch.setenv(PROCESS_ID_ENV_VAR, "9")
    maybe_init_distributed(
        {"distributed": {"coordinator_address": "127.0.0.1:2222", "num_processes": 2, "process_id": 0}}
    )
    assert calls == [("127.0.0.1:2222", 2, 0)]


def test_idempotent(monkeypatch):
    calls = _capture(monkeypatch)
    cfg = {"distributed": {"coordinator_address": "127.0.0.1:9913", "num_processes": 2, "process_id": 0}}
    maybe_init_distributed(cfg)
    maybe_init_distributed(cfg)
    assert len(calls) == 1


def test_init_timeout_raises_barrier_timeout(monkeypatch):
    import time

    def hang(**kwargs):
        time.sleep(30.0)

    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", hang)
    with pytest.raises(BarrierTimeoutError, match="jax_distributed_initialize"):
        maybe_init_distributed(
            {"distributed": {"coordinator_address": "127.0.0.1:9914", "num_processes": 2, "process_id": 0}},
            timeout_s=0.2,
        )
    assert mesh_mod._distributed_initialized is False
