"""Wire layer: framing round-trips, blocking/non-blocking parity, peer death."""

import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.distributed.transport import (
    BATCH_DIGEST_ENV_VAR,
    ChannelClosed,
    FramingError,
    Listener,
    connect,
    decode_tree,
    encode_tree,
    maybe_digest,
    tree_digest,
)


def _roundtrip(tree):
    structure, arrays = encode_tree(tree)
    return decode_tree(structure, [memoryview(a.tobytes()) for a in arrays])


def test_encode_decode_roundtrip_types():
    tree = {
        "f32": np.arange(12, dtype=np.float32).reshape(3, 4),
        "u8": np.full((2, 2, 2), 255, np.uint8),
        "i64": np.int64(-7),
        "f_scalar": np.float32(1.5),
        "nested": {"list": [1, 2.5, None, "text", True], "tuple": (np.zeros(3), "x")},
        "empty": {},
        "bool_arr": np.array([True, False]),
    }
    back = _roundtrip(tree)
    assert back["f32"].dtype == np.float32 and back["f32"].shape == (3, 4)
    np.testing.assert_array_equal(back["f32"], tree["f32"])
    np.testing.assert_array_equal(back["u8"], tree["u8"])
    assert back["i64"] == -7 and back["f_scalar"] == 1.5
    assert back["nested"]["list"] == [1, 2.5, None, "text", True]
    # Tuples become lists on the wire (JSON structure), contents preserved.
    np.testing.assert_array_equal(back["nested"]["tuple"][0], np.zeros(3))
    np.testing.assert_array_equal(back["bool_arr"], tree["bool_arr"])
    # Digest stability holds for array leaves (tuples land as lists and numpy
    # scalars as python scalars on the wire — those digest differently on purpose).
    arrays_only = {k: tree[k] for k in ("f32", "u8", "bool_arr")}
    assert tree_digest(_roundtrip(arrays_only)) == tree_digest(arrays_only)


def test_encode_rejects_reserved_and_nonstring_keys():
    with pytest.raises(TypeError):
        encode_tree({"__nd__": 1})
    with pytest.raises(TypeError):
        encode_tree({1: "x"})


def test_tree_digest_detects_dtype_and_value_changes():
    base = {"a": np.zeros(4, np.float32)}
    assert tree_digest(base) != tree_digest({"a": np.zeros(4, np.float64)})
    assert tree_digest(base) != tree_digest({"a": np.ones(4, np.float32)})
    assert tree_digest(base) == tree_digest({"a": np.zeros(4, np.float32)})


def test_maybe_digest_appends_tagged_lines(tmp_path, monkeypatch):
    sink = tmp_path / "digests.txt"
    monkeypatch.setenv(BATCH_DIGEST_ENV_VAR, str(sink))
    tree = {"a": np.arange(3, dtype=np.float32)}
    maybe_digest("sac:1", tree)
    maybe_digest("sac:2", tree)
    lines = sink.read_text().splitlines()
    assert [ln.split()[0] for ln in lines] == ["sac:1", "sac:2"]
    assert lines[0].split()[1] == tree_digest(tree)


def test_maybe_digest_noop_without_env(tmp_path, monkeypatch):
    monkeypatch.delenv(BATCH_DIGEST_ENV_VAR, raising=False)
    maybe_digest("tag", {"a": np.zeros(1)})  # must not raise or write anywhere


def _server(listener, box, replies=1):
    ch = listener.accept(5.0)
    for _ in range(replies):
        box.append(ch.recv(5.0))
        ch.send("ack", None, n=len(box))
    return ch


def test_channel_send_recv_blocking_and_nonblocking_parity():
    lis = Listener()
    box = []
    server_ch = []
    t = threading.Thread(target=lambda: server_ch.append(_server(lis, box, replies=2)))
    t.start()
    ch = connect("127.0.0.1", lis.port, timeout_s=5.0)
    payload = {"x": np.arange(8, dtype=np.int32)}

    # Blocking recv.
    ch.send("block", payload, i=0)
    kind, meta, body = ch.recv(timeout=5.0)
    assert kind == "ack" and meta["n"] == 1 and body is None

    # Non-blocking path: poll() is False when idle, True once bytes arrive, and
    # the subsequent recv returns the identical framing as the blocking path.
    assert ch.poll(0) is False
    ch.send("block", payload, i=1)
    deadline = time.monotonic() + 5.0
    while not ch.poll(0.05) and time.monotonic() < deadline:
        pass
    assert ch.poll(0) is True
    kind2, meta2, _ = ch.recv(timeout=5.0)
    assert kind2 == "ack" and meta2["n"] == 2
    t.join()

    k, m, p = box[0]
    assert k == "block" and m["i"] == 0
    assert tree_digest(p) == tree_digest(payload)
    ch.close()
    server_ch[0].close()
    lis.close()


def test_channel_close_raises_and_reconnect_works():
    lis = Listener()
    accepted = []
    t = threading.Thread(target=lambda: accepted.append(lis.accept(5.0)))
    t.start()
    ch = connect("127.0.0.1", lis.port, timeout_s=5.0)
    t.join()
    # Peer dies: recv raises ChannelClosed, send raises ChannelClosed, closed=True.
    accepted[0].close()
    with pytest.raises(ChannelClosed):
        ch.recv(timeout=5.0)
    with pytest.raises(ChannelClosed):
        for _ in range(100):  # socket buffering can absorb the first sends
            ch.send("block", {"x": np.zeros(1024)})
            time.sleep(0.005)
    assert ch.closed
    ch.close()

    # The survivor reconnects to the same listener and traffic resumes.
    t2 = threading.Thread(target=lambda: accepted.append(lis.accept(5.0)))
    t2.start()
    ch2 = connect("127.0.0.1", lis.port, timeout_s=5.0)
    t2.join()
    ch2.send("hello", None, actor_id=0)
    kind, meta, _ = accepted[1].recv(5.0)
    assert kind == "hello" and meta["actor_id"] == 0
    ch2.close()
    accepted[1].close()
    lis.close()


def test_bad_magic_raises_framing_error():
    lis = Listener()
    accepted = []
    t = threading.Thread(target=lambda: accepted.append(lis.accept(5.0)))
    t.start()
    import socket

    raw = socket.create_connection(("127.0.0.1", lis.port), timeout=5.0)
    t.join()
    raw.sendall(b"JUNKJUNKJUNKJUNK")
    with pytest.raises(FramingError):
        accepted[0].recv(timeout=5.0)
    raw.close()
    accepted[0].close()
    lis.close()


def test_connect_timeout():
    lis = Listener()
    port = lis.port
    lis.close()  # nobody listening any more
    with pytest.raises((ConnectionError, OSError, TimeoutError)):
        connect("127.0.0.1", port, timeout_s=0.3, retry_interval_s=0.05)


def test_listener_accept_timeout():
    lis = Listener()
    with pytest.raises(TimeoutError):
        lis.accept(0.2)
    lis.close()
