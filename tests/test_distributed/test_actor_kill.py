"""Chaos: SIGKILL one actor process mid-run; the learner must keep learning.

ISSUE 13 acceptance: with 2 actors and ``chaos.kill_actor_at_step`` armed, the
learner's gradient-step counter is STRICTLY increasing across the kill window
(victim dead -> respawn connected) — the surviving actor keeps feeding it, no
barrier wedges, and the launcher's respawn machinery closes the loop."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[2]


def test_actor_sigkill_learner_keeps_stepping(tmp_path):
    summary_path = tmp_path / "summary.json"
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        SHEEPRL_TPU_QUIET="1",
        SHEEPRL_TPU_SEBULBA_SUMMARY=str(summary_path),
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "sheeprl_tpu.sebulba",
            "exp=sac_decoupled",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=8",
            "algo.replay_ratio=1.0",
            "algo.total_steps=128",
            "algo.run_test=False",
            "buffer.size=512",
            "dry_run=False",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "checkpoint.every=64",
            "checkpoint.save_last=True",
            "metric.log_every=32",
            "buffer.memmap=False",
            f"log_root={tmp_path}/logs",
            "distributed.num_actors=2",
            "distributed.connect_timeout_s=60",
            "distributed.respawn_backoff_s=0.2",
            # Deterministic chaos: SIGKILL actor 0 at its 6th iteration,
            # generation 0 only — the respawn runs clean and the experiment ends.
            "chaos.kill_actor_at_step=6",
            "chaos.kill_actor_index=0",
        ],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=540,
    )
    assert proc.returncode == 0, f"sebulba chaos run failed rc={proc.returncode}:\n{proc.stdout[-4000:]}"

    summary = json.loads(summary_path.read_text())
    events = summary["events"]  # [t, actor_id, generation, event]

    # The kill window: actor 0 generation 0 vanishes, generation 1 reconnects.
    kill_t = next(t for t, a, g, e in events if a == 0 and g == 0 and e == "closed")
    respawn_t = next(t for t, a, g, e in events if a == 0 and g == 1 and e == "connected")
    assert respawn_t > kill_t
    assert any(a == 0 and g == 1 and e == "done" for _, a, g, e in events), events
    assert any(a == 1 and e == "done" for _, a, g, e in events), events

    # Learner liveness across the window: >=2 gradient-step trace points strictly
    # inside it, counts strictly increasing (actor 1 kept it fed the whole time).
    trace = summary["grad_step_trace"]  # [t, cumulative_grad_steps]
    inside = [(t, g) for t, g in trace if kill_t < t < respawn_t]
    assert len(inside) >= 2, (
        f"learner starved during the kill window [{kill_t:.2f}, {respawn_t:.2f}]: "
        f"{len(inside)} trace points inside (trace={trace})"
    )
    counts = [g for _, g in inside]
    assert all(b > a for a, b in zip(counts, counts[1:])), counts

    # And the run still completed its full budget after the respawn.
    assert summary["cumulative_grad_steps"] >= counts[-1]
    assert summary["blocks"] > 0
