"""int8-vs-f32 policy parity (howto/precision.md serving acceptance).

Weights-only per-channel int8 quantization of the act-fn kernels must keep the
served policy behaviourally indistinguishable: >= 99% greedy action agreement
on seeded random observations, with the action-distribution drift bounded
(categorical KL for PPO, mean drift for SAC's tanh-squashed Gaussian).
"""

import jax
import numpy as np

from sheeprl_tpu.analysis.ir.synth import (
    box_act_space,
    compose_tiny,
    discrete_act_space,
    tiny_ctx,
    vector_space,
)
from sheeprl_tpu.precision import (
    Int8Weight,
    categorical_kl,
    dequantize_params,
    gaussian_mean_divergence,
)
from sheeprl_tpu.utils.policy import build_policy, parity_stamp, wrap_policy_precision

N_OBS = 512

PPO_TINY = [
    "exp=ppo",
    "env=discrete_dummy",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=32",
    "algo.mlp_layers=1",
    "algo.encoder.mlp_features_dim=32",
    "mesh.precision=fp32",
]
SAC_TINY = [
    "exp=sac",
    "env=continuous_dummy",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=32",
    "mesh.precision=fp32",
]


def _pair(overrides, act_space):
    """(f32 policy, int8 twin of the same params) against explicit tiny spaces."""
    cfg = compose_tiny(list(overrides))
    policy, _ = build_policy(tiny_ctx(cfg), cfg, vector_space(), act_space, greedy=True)
    cfg2 = compose_tiny(list(overrides))
    quantized, _ = build_policy(tiny_ctx(cfg2), cfg2, vector_space(), act_space, greedy=True)
    # identical seeds -> identical params; quantize one copy
    quantized = wrap_policy_precision(quantized, "int8")
    return policy, quantized


def _random_obs(policy, n=N_OBS, seed=0):
    rng = np.random.default_rng(seed)
    return {
        k: rng.standard_normal((n, *shape)).astype(np.dtype(dtype))
        for k, (shape, dtype) in policy.obs_template.items()
    }


def test_ppo_int8_greedy_agreement_and_bounded_kl():
    policy, quantized = _pair(PPO_TINY, discrete_act_space())
    stamp = parity_stamp(quantized, policy, n_obs=N_OBS, seed=0)
    assert stamp["precision"] == "int8" and stamp["reference"] == "f32"
    assert stamp["action_agreement"] >= 0.99, stamp

    # distribution drift: per-head categorical KL on the raw logits
    from sheeprl_tpu.algos.ppo.agent import build_agent

    cfg = compose_tiny(list(PPO_TINY))
    agent, _ = build_agent(tiny_ctx(cfg), discrete_act_space(), vector_space(), cfg)
    obs = _random_obs(policy)
    logits_f32, _ = agent.apply(policy.params, obs)
    logits_int8, _ = agent.apply(dequantize_params(quantized.params), obs)
    for lp, lq in zip(logits_f32, logits_int8):
        assert categorical_kl(lp, lq) <= 1e-3


def test_sac_int8_greedy_agreement_and_bounded_mean_drift():
    policy, quantized = _pair(SAC_TINY, box_act_space())
    stamp = parity_stamp(quantized, policy, n_obs=N_OBS, seed=1)
    assert stamp["action_agreement"] >= 0.99, stamp

    obs = _random_obs(policy, seed=1)
    key = np.zeros((2,), np.uint32)
    a = jax.device_get(policy.act_fn(policy.params, obs, key))
    b = jax.device_get(quantized.act_fn(quantized.params, obs, key))
    assert gaussian_mean_divergence(a, b) <= 5e-3


def test_int8_params_are_quantized_and_smaller():
    policy, quantized = _pair(PPO_TINY, discrete_act_space())
    kernels = [
        leaf
        for leaf in jax.tree.leaves(quantized.params, is_leaf=lambda x: isinstance(x, Int8Weight))
        if isinstance(leaf, Int8Weight)
    ]
    assert kernels, "no kernel was quantized"
    # every quantized kernel's int8 buffer is 4x smaller than its f32 source
    for q in kernels:
        assert q.q.dtype.itemsize == 1 and q.q.shape == q.shape
    # dequantized params track the f32 originals within one quantization step
    dq = dequantize_params(quantized.params)
    for a, b in zip(jax.tree.leaves(policy.params), jax.tree.leaves(dq)):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)), atol=2e-2
        )


def test_bf16_wrap_casts_params_and_tracks_f32_actions():
    cfg = compose_tiny(list(SAC_TINY))
    policy, _ = build_policy(tiny_ctx(cfg), cfg, vector_space(), box_act_space(), greedy=True)
    cfg2 = compose_tiny(list(SAC_TINY) + ["algo.precision=bf16"])
    half, _ = build_policy(tiny_ctx(cfg2), cfg2, vector_space(), box_act_space(), greedy=True)
    half = wrap_policy_precision(half, "bf16")
    import jax.numpy as jnp

    for leaf in jax.tree.leaves(half.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16
    stamp = parity_stamp(half, policy, n_obs=N_OBS, seed=2)
    assert stamp["precision"] == "bf16"
    assert stamp["action_agreement"] >= 0.95, stamp
