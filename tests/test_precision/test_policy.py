"""sheeprl_tpu/precision: policy resolution, loss scaling, int8 quantization,
parity helpers — the unit contracts under the bf16/int8 tier (howto/precision.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.analysis.ir.synth import compose_tiny
from sheeprl_tpu.precision import (
    DynamicLossScale,
    Int8Weight,
    NoOpLossScale,
    action_agreement,
    all_finite,
    categorical_kl,
    dequantize_params,
    quantize_params,
    quantize_weight,
    resolve_policy,
    train_policy,
)


# ------------------------------------------------------------ policy resolution
@pytest.mark.parametrize(
    "spec,param,compute",
    [
        ("f32", jnp.float32, jnp.float32),
        ("fp32", jnp.float32, jnp.float32),
        ("bf16", jnp.float32, jnp.bfloat16),
        ("bf16-mixed", jnp.float32, jnp.bfloat16),
        ("bf16-true", jnp.bfloat16, jnp.bfloat16),
        ("fp16", jnp.float32, jnp.float16),
    ],
)
def test_resolve_policy_dtype_triples(spec, param, compute):
    policy = resolve_policy(spec)
    assert policy.param_dtype == param
    assert policy.compute_dtype == compute


def test_resolve_policy_unknown_raises():
    with pytest.raises(ValueError, match="nonsense"):
        resolve_policy("nonsense")


def test_train_policy_mesh_inherit_and_explicit_override():
    cfg = compose_tiny(["exp=ppo", "env=discrete_dummy", "algo.mlp_keys.encoder=[state]"])
    assert cfg.algo.precision == "mesh"
    # mesh default is bf16-mixed -> inherited bf16 compute
    assert train_policy(cfg).compute_dtype == jnp.bfloat16
    cfg.mesh.precision = "fp32"
    assert train_policy(cfg).compute_dtype == jnp.float32
    # the algo knob overrides the mesh in BOTH directions
    cfg.algo.precision = "bf16"
    assert train_policy(cfg).compute_dtype == jnp.bfloat16
    assert train_policy(cfg).param_dtype == jnp.float32
    cfg.mesh.precision = "bf16-mixed"
    cfg.algo.precision = "f32"
    assert train_policy(cfg).compute_dtype == jnp.float32


def test_train_policy_explicit_fp16_rejected():
    cfg = compose_tiny(["exp=ppo", "env=discrete_dummy", "algo.mlp_keys.encoder=[state]"])
    cfg.algo.precision = "fp16"
    with pytest.raises(ValueError, match="bf16"):
        train_policy(cfg)


def test_cast_to_compute_touches_only_float_leaves():
    policy = resolve_policy("bf16")
    tree = {"w": jnp.ones((2, 2), jnp.float32), "step": jnp.zeros((), jnp.int32)}
    out = policy.cast_to_compute(tree)
    assert out["w"].dtype == jnp.bfloat16
    assert out["step"].dtype == jnp.int32
    back = policy.cast_to_output(out)
    assert back["w"].dtype == jnp.float32


# ------------------------------------------------------------------- loss scale
def test_all_finite_flags_nan_and_inf():
    assert bool(all_finite({"a": jnp.ones(3)}))
    assert not bool(all_finite({"a": jnp.array([1.0, jnp.nan])}))
    assert not bool(all_finite({"a": jnp.array([jnp.inf])}))


def test_dynamic_loss_scale_halves_on_nonfinite_and_doubles_after_period():
    scale = DynamicLossScale(scale=16.0, period=2)
    # non-finite step: halve, reset counter
    down = scale.adjust(jnp.asarray(False))
    assert float(down.loss_scale) == 8.0 and int(down.counter) == 0
    # `period` consecutive finite steps: double
    up = scale
    for _ in range(2):
        up = up.adjust(jnp.asarray(True))
    assert float(up.loss_scale) == 32.0
    # floor: never below min_scale
    floored = DynamicLossScale(scale=1.0, min_scale=1.0).adjust(jnp.asarray(False))
    assert float(floored.loss_scale) == 1.0


def test_loss_scale_is_a_pytree_and_jits():
    scale = DynamicLossScale(scale=4.0)

    @jax.jit
    def step(s, ok):
        return s.adjust(ok)

    out = step(scale, jnp.asarray(True))
    assert float(out.loss_scale) == 4.0 and int(out.counter) == 1
    # scale/unscale round-trip through the no-op policy is the identity
    noop = NoOpLossScale()
    assert float(noop.scale(jnp.float32(3.0))) == 3.0
    assert noop.adjust(jnp.asarray(False)) is noop


# ------------------------------------------------------------------------ int8
def test_int8_weight_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    q = quantize_weight(w)
    assert q.q.dtype == jnp.int8 and q.scale.shape == (1, 32)
    err = jnp.max(jnp.abs(q.dequantize() - w))
    # symmetric per-channel: max error is half a quantization step = scale/2
    assert float(err) <= float(jnp.max(q.scale)) * 0.51 + 1e-7


def test_quantize_params_replaces_only_2d_float_kernels():
    params = {
        "dense": {"kernel": jnp.ones((4, 8)), "bias": jnp.ones((8,))},
        "count": jnp.zeros((), jnp.int32),
    }
    q = quantize_params(params)
    assert isinstance(q["dense"]["kernel"], Int8Weight)
    assert q["dense"]["bias"].dtype == jnp.float32
    assert q["count"].dtype == jnp.int32
    d = dequantize_params(q)
    assert d["dense"]["kernel"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(d["dense"]["kernel"]), 1.0, atol=1e-2)


def test_int8_weight_passes_through_jit_and_dequant_fuses():
    w = jnp.asarray(np.random.default_rng(1).normal(size=(16, 8)).astype(np.float32))
    q = quantize_weight(w)
    x = jnp.ones((4, 16))

    @jax.jit
    def matmul(qw, x):
        return x @ qw.dequantize()

    out = matmul(q, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), atol=0.2)


# ---------------------------------------------------------------------- parity
def test_action_agreement_discrete_and_continuous():
    a = np.array([0, 1, 2, 3])
    assert action_agreement(a, np.array([0, 1, 2, 0])) == 0.75
    # multi-discrete: list of per-head actions, row agrees when ALL heads agree
    assert action_agreement([a, a], [a, np.array([0, 1, 2, 0])]) == 0.75
    c = np.zeros((4, 2), np.float32)
    near = c + 5e-3
    far = c + 5e-1
    assert action_agreement(c, near, continuous=True) == 1.0
    assert action_agreement(c, far, continuous=True) == 0.0


def test_categorical_kl_zero_for_identical_logits():
    logits = jnp.asarray(np.random.default_rng(2).normal(size=(8, 5)).astype(np.float32))
    assert float(categorical_kl(logits, logits)) == pytest.approx(0.0, abs=1e-6)
    shifted = logits + 1.0  # softmax-invariant shift
    assert float(categorical_kl(logits, shifted)) == pytest.approx(0.0, abs=1e-5)
    assert float(categorical_kl(logits, logits * 2.0)) > 0.0
