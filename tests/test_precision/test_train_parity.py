"""bf16-vs-f32 train-step parity (howto/precision.md).

Same seeds, same synthetic envs, mesh pinned to fp32 so ``algo.precision`` is
the ONLY difference: params init identically (param_dtype stays f32 under the
mixed policy), one fused Anakin step runs per tier, and the bf16 losses must
track f32 within the documented tolerance (|Δ| <= 0.05 absolute or 10%
relative) while params and optimizer state stay f32 throughout.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.analysis.ir.synth import compose_tiny, tiny_ctx

# Documented parity tolerance for one update on a random init (losses are O(1)).
LOSS_RTOL = 0.10
LOSS_ATOL = 0.05


def _loss_keys(metrics):
    return sorted(k for k in metrics if k.startswith("Loss/"))


def _assert_losses_track(m_f32, m_bf16):
    keys = _loss_keys(m_f32)
    assert keys, "no Loss/* metrics to compare"
    assert keys == _loss_keys(m_bf16)
    for k in keys:
        a = float(np.asarray(jax.device_get(m_f32[k])).mean())
        b = float(np.asarray(jax.device_get(m_bf16[k])).mean())
        assert abs(a - b) <= LOSS_ATOL + LOSS_RTOL * abs(a), f"{k}: f32={a} bf16={b}"


def _assert_params_f32(params):
    for leaf in jax.tree.leaves(params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32, "mixed policy must keep params f32"


def _run_ppo_step(precision):
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.engine.anakin import (
        anakin_env,
        anakin_mlp_key,
        init_episode_stats,
        make_ppo_anakin_iteration,
        reset_envs,
    )

    cfg = compose_tiny(
        [
            "exp=ppo",
            "env=jax_cartpole",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "env.num_envs=2",
            "mesh.precision=fp32",
            f"algo.precision={precision}",
        ]
    )
    ctx = tiny_ctx(cfg)
    env, env_params = anakin_env(cfg)
    obs_key = anakin_mlp_key(cfg)
    obs_space = gym.spaces.Dict({obs_key: env.observation_space(env_params)})
    agent, params = build_agent(ctx, env.action_space(env_params), obs_space, cfg)
    fns = PPOTrainFns(ctx, agent, cfg, [obs_key], num_updates=4)
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, obs_key)
    env_state, obs0 = reset_envs(env, env_params, 2, jax.random.PRNGKey(1))
    carry = {
        "params": params,
        "opt_state": fns.opt.init(params),
        "env_state": env_state,
        "obs": obs0,
        "key": jax.random.PRNGKey(0),
        "episode_stats": init_episode_stats(2),
    }
    new_carry, metrics = jax.jit(iteration)(carry, 0.2, 0.0)
    return jax.device_get(params), new_carry, metrics


def _run_sac_dispatch(precision):
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.data.device_buffer import DeviceTransitionRing
    from sheeprl_tpu.engine.anakin import (
        anakin_env,
        anakin_mlp_key,
        init_episode_stats,
        make_sac_anakin_dispatch,
        reset_envs,
    )

    cfg = compose_tiny(
        [
            "exp=sac",
            "env=jax_pendulum",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=4",
            "algo.replay_ratio=1",
            "env.num_envs=2",
            "buffer.size=64",
            "mesh.precision=fp32",
            f"algo.precision={precision}",
        ]
    )
    ctx = tiny_ctx(cfg)
    env, env_params = anakin_env(cfg)
    mlp_key = anakin_mlp_key(cfg)
    obs_space_box = env.observation_space(env_params)
    act_space = env.action_space(env_params)
    obs_space = gym.spaces.Dict({mlp_key: obs_space_box})
    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    params = jax.tree.map(jnp.copy, params)
    obs_dim = int(np.prod(obs_space_box.shape))
    act_dim = int(np.prod(act_space.shape))
    ring = DeviceTransitionRing(
        32,
        2,
        {
            "obs": ((obs_dim,), jnp.float32),
            "next_obs": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
    )
    actor_opt, critic_opt, alpha_opt, builder = make_sac_anakin_dispatch(
        env, env_params, actor, critic, cfg, act_space, ring, 4
    )
    env_state, obs0 = reset_envs(env, env_params, 2, jax.random.PRNGKey(1))
    carry = {
        "params": params,
        "opt_state": {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        },
        "env_state": env_state,
        "obs": obs0,
        "ring": ring.arrays,
        "rows_added": jnp.zeros((), jnp.int32),
        "gstep": jnp.zeros((), jnp.int32),
        "key": jax.random.PRNGKey(0),
        "episode_stats": init_episode_stats(2),
    }
    init_params = jax.device_get(params)
    new_carry, metrics = jax.jit(builder(8, 1, True), donate_argnums=(0,))(carry)
    return init_params, new_carry, metrics


def test_ppo_bf16_step_tracks_f32_losses():
    init_f32, carry_f32, m_f32 = _run_ppo_step("f32")
    init_bf16, carry_bf16, m_bf16 = _run_ppo_step("bf16")
    # identical init: param_dtype is f32 under BOTH tiers and seeds match
    for a, b in zip(jax.tree.leaves(init_f32), jax.tree.leaves(init_bf16)):
        np.testing.assert_array_equal(a, b)
    _assert_losses_track(m_f32, m_bf16)
    _assert_params_f32(carry_bf16["params"])
    _assert_params_f32(carry_bf16["opt_state"])
    # the updated params stay close between tiers after one step
    for a, b in zip(jax.tree.leaves(carry_f32["params"]), jax.tree.leaves(carry_bf16["params"])):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)), atol=5e-2
        )


def test_sac_bf16_dispatch_tracks_f32_losses():
    init_f32, carry_f32, m_f32 = _run_sac_dispatch("f32")
    init_bf16, carry_bf16, m_bf16 = _run_sac_dispatch("bf16")
    for a, b in zip(jax.tree.leaves(init_f32), jax.tree.leaves(init_bf16)):
        np.testing.assert_array_equal(a, b)
    _assert_losses_track(m_f32, m_bf16)
    _assert_params_f32(carry_bf16["params"])
    _assert_params_f32(carry_bf16["opt_state"])
