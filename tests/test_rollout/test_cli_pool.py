"""End-to-end: a training loop through the real CLI with the EnvPool backend and
the acting pipeline enabled (env.pool.enabled=True, rollout.pipeline_depth=1)."""

from __future__ import annotations

from sheeprl_tpu.cli import run


def test_ppo_dry_run_with_envpool_and_pipeline(tmp_path):
    run(
        [
            "exp=ppo",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "env.pool.enabled=True",
            "env.pool.num_workers=2",
            "rollout.pipeline_depth=1",
            "rollout.step_timeout_s=60",
            "dry_run=True",
            "env.num_envs=2",
            "env.capture_video=False",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            "metric.log_every=1",
            f"log_root={tmp_path}",
            "buffer.memmap=False",
        ]
    )
