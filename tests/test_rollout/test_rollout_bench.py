"""Tier-1 smoke of benchmarks/rollout_bench.py: tiny dummy-env invocation, JSON
row shape compatible with the BENCH_*.json trajectory."""

from __future__ import annotations

import json
import os
import sys


def _load_bench_module():
    bench_dir = os.path.join(os.path.dirname(__file__), "..", "..", "benchmarks")
    sys.path.insert(0, os.path.abspath(bench_dir))
    try:
        import rollout_bench
    finally:
        sys.path.pop(0)
    return rollout_bench


def test_rollout_bench_smoke(capsys, tmp_path):
    rollout_bench = _load_bench_module()
    out_path = tmp_path / "rollout_bench.json"
    rates = rollout_bench.main(
        [
            "--num-envs", "2",
            "--steps", "4",
            "--warmup-steps", "1",
            "--step-ms", "0",
            "--screen-size", "16",
            "--ep-len", "8",
            "--backends", "sync,pool",
            "--json-out", str(out_path),
        ]
    )
    assert set(rates) == {"sync", "pool"}
    assert all(v > 0 for v in rates.values())

    # stdout: one JSON object per line, BENCH_*-style rows
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    rows = [json.loads(ln) for ln in lines]
    metrics = {r["metric"] for r in rows}
    assert "rollout_env_steps_per_sec_sync" in metrics
    assert "rollout_env_steps_per_sec_pool" in metrics
    assert "rollout_envpool_speedup_vs_sync" in metrics
    for r in rows:
        assert {"metric", "value", "unit"} <= set(r)
        assert isinstance(r["value"], (int, float))

    saved = json.loads(out_path.read_text())
    assert [r["metric"] for r in saved] == [r["metric"] for r in rows]
