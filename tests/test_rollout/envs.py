"""Misbehaving envs for the EnvPool robustness tests."""

from __future__ import annotations

import os
import time

from sheeprl_tpu.envs.dummy import DiscreteDummyEnv


class HangingEnv(DiscreteDummyEnv):
    """Blocks forever on its ``hang_at``-th step (0 disables) — simulates a wedged
    simulator; only a process kill gets past it."""

    def __init__(self, hang_at: int = 0, **kwargs):
        super().__init__(**kwargs)
        self._hang_at = hang_at
        self._steps_taken = 0

    def step(self, action):
        self._steps_taken += 1
        if self._hang_at and self._steps_taken == self._hang_at:
            time.sleep(3600)
        return super().step(action)


class CrashingEnv(DiscreteDummyEnv):
    """Kills its own process on the ``crash_at``-th step (0 disables) — simulates a
    segfault/OOM-killed worker, which no in-process except block can catch."""

    def __init__(self, crash_at: int = 0, **kwargs):
        super().__init__(**kwargs)
        self._crash_at = crash_at
        self._steps_taken = 0

    def step(self, action):
        self._steps_taken += 1
        if self._crash_at and self._steps_taken == self._crash_at:
            os._exit(13)
        return super().step(action)
