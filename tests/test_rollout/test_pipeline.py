"""PipelinedPlayer semantics: depth-0 bit-parity with the synchronous acting
path, and the documented lag/replay behavior at depth >= 1."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from gymnasium.vector import AutoresetMode, SyncVectorEnv

from sheeprl_tpu.envs.dummy import DiscreteDummyEnv
from sheeprl_tpu.rollout import EnvPool, PipelinedPlayer

N_ENVS = 2
EP_LEN = 5


def _thunks():
    return [lambda: DiscreteDummyEnv(n_steps=EP_LEN, action_dim=2) for _ in range(N_ENVS)]


def _make_policy(params_scale=1.0):
    """A jitted toy policy: action logits from the state obs; deterministic."""

    @jax.jit
    def policy_fn(state):
        logits = jnp.stack([jnp.sin(state[:, 0] * params_scale), jnp.cos(state[:, 0])], -1)
        return logits

    def policy(obs):
        return policy_fn(jnp.asarray(obs["state"]))

    def post(fetched):
        logits = np.asarray(fetched)
        return logits.argmax(-1), logits

    return policy, post


def _run_trajectory(envs, player, steps):
    obs, _ = envs.reset(seed=3)
    traj = []
    for _ in range(steps):
        env_actions, payload, (obs, rew, term, trunc, _info) = player.step(obs)
        traj.append((env_actions.copy(), payload.copy(), obs["state"].copy(), rew.copy(), term.copy(), trunc.copy()))
    return traj


def test_depth0_trajectory_parity_with_manual_loop():
    """pipeline_depth=0 must reproduce the hand-rolled dispatch->device_get->step
    sequence bit for bit (obs, rewards, dones, episode boundaries)."""
    policy, post = _make_policy()

    # manual synchronous rollout (the historical acting path)
    envs = SyncVectorEnv(_thunks(), autoreset_mode=AutoresetMode.SAME_STEP)
    obs, _ = envs.reset(seed=3)
    manual = []
    for _ in range(2 * EP_LEN + 3):
        logits = np.asarray(jax.device_get(policy(obs)))
        acts = logits.argmax(-1)
        obs, rew, term, trunc, _info = envs.step(acts)
        manual.append((acts.copy(), logits.copy(), obs["state"].copy(), rew.copy(), term.copy(), trunc.copy()))
    envs.close()

    # the same through PipelinedPlayer at depth 0, over an EnvPool
    pool = EnvPool(_thunks(), num_workers=2, step_timeout_s=30.0)
    player = PipelinedPlayer(pool, policy, post, depth=0)
    piped = _run_trajectory(pool, player, 2 * EP_LEN + 3)
    pool.close()

    for step, (m, p) in enumerate(zip(manual, piped)):
        for j, name in enumerate(("actions", "logits", "state", "rewards", "terminated", "truncated")):
            np.testing.assert_array_equal(m[j], p[j], err_msg=f"step {step}: {name}")


def test_depth1_replays_then_lags():
    """depth=1: step 0 acts on obs 0; step 1 replays the initial action while the
    pipeline fills; step t>=2 applies the action computed from obs t-1."""
    dispatched = []

    def policy(obs):
        dispatched.append(float(obs["state"][0, 0]))
        return jnp.asarray(obs["state"][:, 0].astype(np.int64) % 2)

    def post(fetched):
        a = np.asarray(fetched)
        return a, a

    pool = EnvPool(_thunks(), num_workers=2, step_timeout_s=30.0)
    player = PipelinedPlayer(pool, policy, post, depth=1)
    obs, _ = pool.reset(seed=0)
    applied = []
    for _ in range(5):
        env_actions, _payload, (obs, *_rest) = player.step(obs)
        applied.append(int(env_actions[0]))
    pool.close()

    # the policy was dispatched on every (fresh) observation...
    assert dispatched == [0.0, 1.0, 2.0, 3.0, 4.0]
    # ...but the applied action stream is: fresh, replay, then lag-1.
    assert applied == [0, 0, 1, 0, 1]


def test_depth_validation_and_reset():
    policy, post = _make_policy()
    pool = EnvPool(_thunks(), num_workers=1, step_timeout_s=30.0)
    try:
        import pytest

        with pytest.raises(ValueError):
            PipelinedPlayer(pool, policy, post, depth=-1)
        player = PipelinedPlayer(pool, policy, post, depth=2)
        obs, _ = pool.reset(seed=0)
        player.act(obs)
        assert len(player._queue) == 1
        player.reset_pipeline()
        assert len(player._queue) == 0
    finally:
        pool.close()


def test_act_env_step_split_matches_combined():
    """The two-phase API (act + env_step, used by dreamer_v3 to keep the train
    dispatch between them) yields the same trajectory as combined step()."""
    policy, post = _make_policy()

    pool_a = EnvPool(_thunks(), num_workers=2, step_timeout_s=30.0)
    player_a = PipelinedPlayer(pool_a, policy, post, depth=0)
    combined = _run_trajectory(pool_a, player_a, EP_LEN + 2)
    pool_a.close()

    pool_b = EnvPool(_thunks(), num_workers=2, step_timeout_s=30.0)
    player_b = PipelinedPlayer(pool_b, policy, post, depth=0)
    obs, _ = pool_b.reset(seed=3)
    split = []
    for _ in range(EP_LEN + 2):
        env_actions, payload = player_b.act(obs)
        obs, rew, term, trunc, _info = player_b.env_step(env_actions)
        split.append((env_actions.copy(), payload.copy(), obs["state"].copy(), rew.copy(), term.copy(), trunc.copy()))
    pool_b.close()

    for m, p in zip(combined, split):
        for j in range(6):
            np.testing.assert_array_equal(m[j], p[j])
