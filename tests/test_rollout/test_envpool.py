"""Shared-memory round-trip correctness: EnvPool vs SyncVectorEnv(SAME_STEP).

The pool's contract is bit-equality with the existing ``utils/env.py`` vector
path under a fixed seed: batched obs layout and values, float64 rewards, bool
done flags, ``final_obs``/``final_info`` payloads and episode-statistics infos.
"""

from __future__ import annotations

import gymnasium as gym
import numpy as np
import pytest
from gymnasium.vector import AutoresetMode, SyncVectorEnv

from sheeprl_tpu.envs.dummy import ContinuousDummyEnv, DiscreteDummyEnv, MultiDiscreteDummyEnv
from sheeprl_tpu.rollout import EnvPool

N_ENVS = 3
EP_LEN = 4  # DiscreteDummyEnv terminates at n_steps+1 -> several boundaries in a short run


def _thunks(cls, **kwargs):
    def mk(i):
        def thunk():
            return gym.wrappers.RecordEpisodeStatistics(cls(**kwargs))

        return thunk

    return [mk(i) for i in range(N_ENVS)]


def _assert_info_equal(si: dict, pi: dict) -> None:
    assert set(si) == set(pi)
    for k in si:
        sv, pv = si[k], pi[k]
        if k == "final_obs":
            for a, b in zip(sv, pv):
                if a is None:
                    assert b is None
                else:
                    assert set(a) == set(b)
                    for kk in a:
                        np.testing.assert_array_equal(a[kk], b[kk])
        elif isinstance(sv, dict):
            # episode stats: 't' is wall-clock elapsed time, nondeterministic even
            # between two SyncVectorEnv instances — compare everything else.
            def scrub(d):
                return {
                    kk: scrub(vv) if isinstance(vv, dict) else np.asarray(vv).tolist()
                    for kk, vv in d.items()
                    if kk not in ("t", "_t")
                }

            assert scrub(sv) == scrub(pv)
        else:
            np.testing.assert_array_equal(np.asarray(sv), np.asarray(pv))


@pytest.mark.parametrize(
    "cls,kwargs,sample_space",
    [
        (DiscreteDummyEnv, dict(n_steps=EP_LEN, action_dim=3), gym.spaces.Discrete(3)),
        (MultiDiscreteDummyEnv, dict(n_steps=EP_LEN, action_dims=[2, 3]), gym.spaces.MultiDiscrete([2, 3])),
        (ContinuousDummyEnv, dict(n_steps=EP_LEN, action_dim=2), gym.spaces.Box(-1.0, 1.0, (2,), np.float32)),
    ],
)
def test_envpool_matches_sync_vector_env(cls, kwargs, sample_space):
    thunks = _thunks(cls, **kwargs)
    sync = SyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)
    pool = EnvPool(thunks, num_workers=2, step_timeout_s=30.0)
    try:
        so, si = sync.reset(seed=11)
        po, pi = pool.reset(seed=11)
        assert set(so) == set(po)
        for k in so:
            np.testing.assert_array_equal(so[k], po[k])
            assert so[k].dtype == po[k].dtype
        _assert_info_equal(si, pi)

        sample_space.seed(123)
        for step in range(2 * (EP_LEN + 2)):  # crosses at least one autoreset boundary
            actions = np.stack([sample_space.sample() for _ in range(N_ENVS)])
            s_obs, s_rew, s_term, s_trunc, s_info = sync.step(actions.copy())
            p_obs, p_rew, p_term, p_trunc, p_info = pool.step(actions.copy())
            for k in s_obs:
                np.testing.assert_array_equal(s_obs[k], p_obs[k])
            np.testing.assert_array_equal(s_rew, p_rew)
            assert s_rew.dtype == p_rew.dtype == np.float64
            np.testing.assert_array_equal(s_term, p_term)
            np.testing.assert_array_equal(s_trunc, p_trunc)
            assert s_term.dtype == p_term.dtype == np.bool_
            _assert_info_equal(s_info, p_info)
    finally:
        sync.close()
        pool.close()


def test_envpool_same_step_autoreset_semantics():
    """SAME_STEP contract as documented in utils/env.py: on the done step the
    returned obs is the fresh reset obs and the true final obs rides info."""
    thunks = _thunks(DiscreteDummyEnv, n_steps=EP_LEN)
    pool = EnvPool(thunks, num_workers=2, step_timeout_s=30.0)
    try:
        obs, _ = pool.reset(seed=0)
        done_seen = False
        for _ in range(EP_LEN + 2):
            obs, rew, term, trunc, info = pool.step(np.zeros(N_ENVS, dtype=np.int64))
            if term.any():
                done_seen = True
                # reset obs on the done step: dummy env restarts its counter at 0
                assert (obs["state"][term] == 0.0).all()
                assert "final_obs" in info
                for i in np.nonzero(term)[0]:
                    final = info["final_obs"][i]
                    assert final is not None
                    # the true final obs carries the last step counter, not 0
                    assert (np.asarray(final["state"]) != 0.0).all()
                assert "final_info" in info and "episode" in info["final_info"]
        assert done_seen
    finally:
        pool.close()


def test_envpool_reset_seeding_matches_sync():
    """reset(seed=s) must seed env i with s+i, like gymnasium's vector envs."""
    thunks = _thunks(DiscreteDummyEnv, n_steps=EP_LEN)
    sync = SyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)
    pool = EnvPool(thunks, num_workers=3, step_timeout_s=30.0)
    try:
        for seed in (0, 42):
            so, _ = sync.reset(seed=seed)
            po, _ = pool.reset(seed=seed)
            for k in so:
                np.testing.assert_array_equal(so[k], po[k])
    finally:
        sync.close()
        pool.close()


def test_envpool_obs_snapshots_do_not_alias():
    """Returned observations must be copies: callers keep them across steps while
    workers overwrite the shared slab in place."""
    thunks = _thunks(DiscreteDummyEnv, n_steps=16)
    pool = EnvPool(thunks, num_workers=1, step_timeout_s=30.0)
    try:
        obs0, _ = pool.reset(seed=0)
        kept = {k: v.copy() for k, v in obs0.items()}
        pool.step(np.zeros(N_ENVS, dtype=np.int64))
        for k in kept:
            np.testing.assert_array_equal(obs0[k], kept[k])
    finally:
        pool.close()


def test_envpool_worker_partitioning_and_close():
    thunks = _thunks(DiscreteDummyEnv, n_steps=EP_LEN)
    pool = EnvPool(thunks, num_workers=2, step_timeout_s=30.0)
    sizes = [w.num_envs for w in pool._workers]
    assert sum(sizes) == N_ENVS and max(sizes) - min(sizes) <= 1
    pool.reset(seed=0)
    procs = [w.proc for w in pool._workers]
    assert all(p.is_alive() for p in procs)
    pool.close()
    assert all(not p.is_alive() for p in procs)
    pool.close()  # idempotent


def test_envpool_metrics_shape():
    thunks = _thunks(DiscreteDummyEnv, n_steps=EP_LEN)
    pool = EnvPool(thunks, num_workers=2, step_timeout_s=30.0)
    try:
        pool.reset(seed=0)
        pool.step(np.zeros(N_ENVS, dtype=np.int64))
        m = pool.rollout_metrics()
        assert m["Rollout/env_steps"] == 1.0
        assert m["Rollout/worker_restarts"] == 0.0
        assert m["Rollout/num_workers"] == 2.0
    finally:
        pool.close()


def test_rollout_metrics_helper_noop_for_plain_envs():
    from sheeprl_tpu.rollout import rollout_metrics

    thunks = _thunks(DiscreteDummyEnv, n_steps=EP_LEN)
    sync = SyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)
    try:
        assert rollout_metrics(sync) == {}
    finally:
        sync.close()
