"""Watchdog / restart / abort behavior of the EnvPool robustness layer."""

from __future__ import annotations

import numpy as np
import pytest

from sheeprl_tpu.rollout import EnvPool, RolloutAbortError
from tests.test_rollout.envs import CrashingEnv, HangingEnv


def test_watchdog_restarts_hung_worker(recwarn):
    """A worker stuck inside env.step past step_timeout_s is killed and replaced;
    its envs surface the break as truncated=True + info['rollout_restart']."""
    thunks = [
        lambda: HangingEnv(hang_at=2, n_steps=32),
        lambda: HangingEnv(hang_at=0, n_steps=32),
    ]
    pool = EnvPool(thunks, num_workers=2, step_timeout_s=1.5, max_restarts=2, restart_backoff_s=0.0)
    try:
        obs, _ = pool.reset(seed=5)
        obs, rew, term, trunc, info = pool.step(np.zeros(2, np.int64))
        assert not trunc.any()
        obs, rew, term, trunc, info = pool.step(np.zeros(2, np.int64))  # env 0 hangs here
        assert trunc[0] and not trunc[1]
        assert not term.any()
        assert rew[0] == 0.0
        assert info["rollout_restart"][0] and not info["rollout_restart"][1]
        # the restarted env delivered a fresh reset obs; the healthy one kept going
        assert obs["state"][0, 0] == 0.0
        assert obs["state"][1, 0] == 2.0
        m = pool.rollout_metrics()
        assert m["Rollout/worker_restarts"] == 1.0
        assert m["Rollout/worker_timeouts"] == 1.0
        # pool keeps stepping after the restart
        obs, *_ = pool.step(np.zeros(2, np.int64))
        assert obs["state"][1, 0] == 3.0
    finally:
        pool.close(terminate=True)


def test_watchdog_restarts_crashed_worker(recwarn):
    """A worker process that dies outright (os._exit inside env.step) is detected
    without waiting for the full step timeout and restarted."""
    thunks = [lambda: CrashingEnv(crash_at=2, n_steps=32)]
    pool = EnvPool(thunks, num_workers=1, step_timeout_s=30.0, max_restarts=2, restart_backoff_s=0.0)
    try:
        pool.reset(seed=1)
        pool.step(np.zeros(1, np.int64))
        obs, rew, term, trunc, info = pool.step(np.zeros(1, np.int64))  # crash
        assert trunc[0]
        assert info["rollout_restart"][0]
        m = pool.rollout_metrics()
        assert m["Rollout/worker_restarts"] == 1.0
        assert m["Rollout/worker_crashes"] == 1.0
    finally:
        pool.close(terminate=True)


def test_max_restarts_budget_aborts(recwarn):
    """Past the restart budget the pool tears down and raises RolloutAbortError
    whose message quotes a per-worker post-mortem (restart/timeout/crash counts
    and heartbeat age) — the flaky worker is identifiable from the traceback."""
    thunks = [lambda: CrashingEnv(crash_at=1, n_steps=32)]
    pool = EnvPool(thunks, num_workers=1, step_timeout_s=30.0, max_restarts=0, restart_backoff_s=0.0)
    pool.reset(seed=0)
    with pytest.raises(RolloutAbortError) as exc_info:
        pool.step(np.zeros(1, np.int64))
    assert pool.closed
    assert all(w.proc is None or not w.proc.is_alive() for w in pool._workers)
    msg = str(exc_info.value)
    assert "totals: restarts=" in msg
    assert "worker 0: restarts=" in msg
    assert "last_heartbeat" in msg


def test_restart_reseeds_with_generation_offset(recwarn):
    """Replacement workers reset with base_seed + generation * stride, so a
    restarted env does not replay the exact pre-crash episode stream."""
    thunks = [lambda: CrashingEnv(crash_at=3, n_steps=32)]
    pool = EnvPool(thunks, num_workers=1, step_timeout_s=30.0, max_restarts=3, restart_backoff_s=0.0)
    try:
        pool.reset(seed=7)
        assert pool._env_seeds == [7]
        for _ in range(3):
            pool.step(np.zeros(1, np.int64))
        w = pool._workers[0]
        assert w.generation == 1
        assert pool._worker_seeds(w) == [7 + 7919]
    finally:
        pool.close(terminate=True)


def test_heartbeat_ages_are_fresh():
    thunks = [lambda: HangingEnv(hang_at=0, n_steps=32)]
    pool = EnvPool(thunks, num_workers=1, step_timeout_s=30.0, heartbeat_interval_s=0.05)
    try:
        pool.reset(seed=0)
        ages = pool.heartbeat_ages()
        assert ages.shape == (1,)
        assert np.isfinite(ages).all()
        assert (ages < 10.0).all()
    finally:
        pool.close(terminate=True)
