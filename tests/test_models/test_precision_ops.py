"""bf16 parity for the fused Pallas ops (howto/precision.md satellite).

Both kernels upcast to f32 in VMEM and cast back to the state dtype on the way
out, so feeding bf16 operands must track the f32 XLA reference within bf16
rounding — forward AND the hand-derived VJPs.  Off-TPU this runs the kernels in
interpreter mode: the exact code path the TPU executes, minus Mosaic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.gru import fused_layernorm_gru, reference_layernorm_gru
from sheeprl_tpu.ops.rssm_step import fused_gru_step, reference_gru_step

# bf16 has an 8-bit mantissa (~0.4% relative); the chained gate nonlinearities
# keep everything O(1) so absolute tolerances are meaningful.
FWD_ATOL = 2e-2
GRAD_ATOL = 6e-2


def _gru_operands(rng, batch=8, hidden=128, dtype=jnp.bfloat16):
    proj = jnp.asarray(rng.normal(size=(batch, 3 * hidden)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(batch, hidden)).astype(np.float32))
    gamma = jnp.asarray(rng.normal(1.0, 0.1, size=(3 * hidden,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(0.0, 0.1, size=(3 * hidden,)).astype(np.float32))
    f32 = (proj, h, gamma, beta)
    return tuple(x.astype(dtype) for x in f32), f32


def _step_operands(rng, batch=8, k=96, hidden=64, dtype=jnp.bfloat16):
    xh = jnp.asarray(rng.normal(size=(batch, k)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(batch, hidden)).astype(np.float32))
    w = jnp.asarray(rng.normal(scale=k**-0.5, size=(k, 3 * hidden)).astype(np.float32))
    gamma = jnp.asarray(rng.normal(1.0, 0.1, size=(3 * hidden,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(0.0, 0.1, size=(3 * hidden,)).astype(np.float32))
    f32 = (xh, h, w, gamma, beta)
    return tuple(x.astype(dtype) for x in f32), f32


def test_fused_gru_bf16_forward_tracks_f32_reference():
    bf16, f32 = _gru_operands(np.random.default_rng(0))
    out = fused_layernorm_gru(*bf16)
    assert out.dtype == jnp.bfloat16
    ref = reference_layernorm_gru(*f32)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=FWD_ATOL
    )


def test_fused_gru_bf16_vjp_tracks_f32_reference():
    bf16, f32 = _gru_operands(np.random.default_rng(1))

    def loss(fn, args):
        return jnp.sum(fn(*args).astype(jnp.float32))

    grads = jax.grad(lambda *a: loss(fused_layernorm_gru, a), argnums=(0, 1, 2, 3))(*bf16)
    ref = jax.grad(lambda *a: loss(reference_layernorm_gru, a), argnums=(0, 1, 2, 3))(*f32)
    for g, r, name in zip(grads, ref, ["proj", "h", "gamma", "beta"]):
        assert g.dtype == jnp.bfloat16, name
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32), atol=GRAD_ATOL, err_msg=name
        )


def test_fused_rssm_step_bf16_forward_tracks_f32_reference():
    bf16, f32 = _step_operands(np.random.default_rng(2))
    out = fused_gru_step(*bf16)
    assert out.dtype == jnp.bfloat16
    ref = reference_gru_step(*f32)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=FWD_ATOL
    )


def test_fused_rssm_step_bf16_vjp_tracks_f32_reference():
    bf16, f32 = _step_operands(np.random.default_rng(3))

    def loss(fn, args):
        return jnp.sum(fn(*args).astype(jnp.float32))

    grads = jax.grad(lambda *a: loss(fused_gru_step, a), argnums=(0, 1, 2, 3, 4))(*bf16)
    ref = jax.grad(lambda *a: loss(reference_gru_step, a), argnums=(0, 1, 2, 3, 4))(*f32)
    for g, r, name in zip(grads, ref, ["xh", "h", "w", "gamma", "beta"]):
        assert g.dtype == jnp.bfloat16, name
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32), atol=GRAD_ATOL, err_msg=name
        )


@pytest.mark.parametrize("batch", [8, 16])
def test_fused_gru_bf16_matches_its_own_f32_run(batch):
    """The kernel's bf16 result must equal its OWN f32 result within rounding —
    pins that precision loss comes only from the operand dtype, not a divergent
    code path."""
    bf16, f32 = _gru_operands(np.random.default_rng(4), batch=batch)
    np.testing.assert_allclose(
        np.asarray(fused_layernorm_gru(*bf16), np.float32),
        np.asarray(fused_layernorm_gru(*f32), np.float32),
        atol=FWD_ATOL,
    )
