"""MinedojoActor hierarchical masking (reference dreamer_v3/agent.py:848-932)."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import MinedojoActor


def _build(actions_dim=(19, 6, 10)):
    actor = MinedojoActor(actions_dim=actions_dim, is_continuous=False, dense_units=8, mlp_layers=1)
    params = actor.init(jax.random.PRNGKey(0), jnp.zeros((4, 16)), jax.random.PRNGKey(1))
    return actor, params


def test_action_type_mask_is_respected():
    actor, params = _build()
    mask = {
        # only actions 3 and 15 allowed
        "mask_action_type": jnp.zeros((4, 19), bool).at[:, 3].set(True).at[:, 15].set(True),
        "mask_craft_smelt": jnp.ones((4, 6), bool),
        "mask_equip_place": jnp.ones((4, 10), bool),
        "mask_destroy": jnp.ones((4, 10), bool),
    }
    for seed in range(5):
        actions, _ = actor.apply(params, jnp.ones((4, 16)), jax.random.PRNGKey(seed), False, mask)
        chosen = np.asarray(actions[0].argmax(-1))
        assert np.isin(chosen, [3, 15]).all(), chosen


def test_craft_mask_applies_only_when_crafting():
    actor, params = _build()
    base = {
        "mask_craft_smelt": jnp.zeros((4, 6), bool).at[:, 2].set(True),
        "mask_equip_place": jnp.ones((4, 10), bool),
        "mask_destroy": jnp.ones((4, 10), bool),
    }
    # Force the craft action (15): the craft argument must obey its mask.
    mask = {**base, "mask_action_type": jnp.zeros((4, 19), bool).at[:, 15].set(True)}
    for seed in range(5):
        actions, _ = actor.apply(params, jnp.ones((4, 16)), jax.random.PRNGKey(seed), False, mask)
        assert (np.asarray(actions[1].argmax(-1)) == 2).all()
    # Force a movement action (1): the craft argument is unconstrained.
    mask = {**base, "mask_action_type": jnp.zeros((4, 19), bool).at[:, 1].set(True)}
    seen = set()
    for seed in range(20):
        actions, _ = actor.apply(params, jnp.ones((4, 16)), jax.random.PRNGKey(seed), False, mask)
        seen.update(np.asarray(actions[1].argmax(-1)).tolist())
    assert len(seen) > 1, "craft head should be unconstrained for non-craft actions"


def test_destroy_mask_applies_for_destroy_action():
    actor, params = _build()
    mask = {
        "mask_action_type": jnp.zeros((4, 19), bool).at[:, 18].set(True),  # destroy only
        "mask_craft_smelt": jnp.ones((4, 6), bool),
        "mask_equip_place": jnp.zeros((4, 10), bool).at[:, 1].set(True),
        "mask_destroy": jnp.zeros((4, 10), bool).at[:, 7].set(True),
    }
    for seed in range(5):
        actions, _ = actor.apply(params, jnp.ones((4, 16)), jax.random.PRNGKey(seed), False, mask)
        assert (np.asarray(actions[2].argmax(-1)) == 7).all()


def test_minedojo_actor_v2_masking():
    from sheeprl_tpu.algos.dreamer_v2.agent import MinedojoActorV2

    actor = MinedojoActorV2(actions_dim=(19, 6, 10), dense_units=8, mlp_layers=1)
    params = actor.init(jax.random.PRNGKey(0), jnp.zeros((4, 16)), jax.random.PRNGKey(1))
    mask = {
        "mask_action_type": jnp.zeros((4, 19), bool).at[:, 15].set(True),
        "mask_craft_smelt": jnp.zeros((4, 6), bool).at[:, 3].set(True),
        "mask_equip_place": jnp.ones((4, 10), bool),
        "mask_destroy": jnp.ones((4, 10), bool),
    }
    for seed in range(5):
        actions, _ = actor.apply(params, jnp.ones((4, 16)), jax.random.PRNGKey(seed), False, mask)
        assert (np.asarray(actions[0].argmax(-1)) == 15).all()
        assert (np.asarray(actions[1].argmax(-1)) == 3).all()
