"""Model-block shape/semantic tests (reference: ``tests/test_models/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models.blocks import (
    CNN,
    MLP,
    DeCNN,
    LayerNormGRUCell,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    cnn_obs_to_nhwc,
)


def test_mlp_shapes():
    m = MLP(hidden_sizes=(32, 32), output_dim=5, activation="tanh", layer_norm=True)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((4, 10)))
    out = m.apply(params, jnp.ones((4, 10)))
    assert out.shape == (4, 5)


def test_mlp_no_output_head():
    m = MLP(hidden_sizes=(16,))
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 3)))
    assert m.apply(params, jnp.ones((2, 3))).shape == (2, 16)


def test_cnn_and_decnn_shapes():
    cnn = CNN(channels=(8, 16), kernels=(3,), strides=(2,), paddings=("SAME",))
    params = cnn.init(jax.random.PRNGKey(0), jnp.zeros((2, 16, 16, 3)))
    out = cnn.apply(params, jnp.zeros((2, 16, 16, 3)))
    assert out.shape == (2, 4, 4, 16)

    dec = DeCNN(channels=(8, 3), kernels=(3,), strides=(2,))
    dparams = dec.init(jax.random.PRNGKey(0), out)
    rec = dec.apply(dparams, out)
    assert rec.shape == (2, 16, 16, 3)


def test_nature_cnn_shape():
    m = NatureCNN(features_dim=128)
    x = jnp.zeros((3, 64, 64, 4))
    params = m.init(jax.random.PRNGKey(0), x)
    assert m.apply(params, x).shape == (3, 128)


def test_layernorm_gru_cell():
    cell = LayerNormGRUCell(hidden_size=16)
    h = jnp.zeros((4, 16))
    x = jnp.ones((4, 8))
    params = cell.init(jax.random.PRNGKey(0), h, x)
    h1, out = cell.apply(params, h, x)
    assert h1.shape == (4, 16)
    assert np.allclose(np.asarray(h1), np.asarray(out))
    # Must be scannable over time.
    xs = jnp.ones((5, 4, 8))
    h_final, _ = jax.lax.scan(lambda c, xt: cell.apply(params, c, xt), h, xs)
    assert h_final.shape == (4, 16)


def test_cnn_obs_to_nhwc_plain_and_stacked():
    x = jnp.zeros((2, 3, 8, 8), dtype=jnp.uint8)
    out = cnn_obs_to_nhwc(x)
    assert out.shape == (2, 8, 8, 3)
    assert out.dtype == jnp.float32
    stacked = jnp.zeros((2, 4, 3, 8, 8), dtype=jnp.uint8)
    out = cnn_obs_to_nhwc(stacked, stacked=True)
    assert out.shape == (2, 8, 8, 12)
    # A 5-D sequence batch without the flag keeps time/batch separate.
    seq = jnp.zeros((5, 2, 3, 8, 8), dtype=jnp.uint8)
    out = cnn_obs_to_nhwc(seq)
    assert out.shape == (5, 2, 8, 8, 3)


@pytest.mark.parametrize("lead", [(2,), (3, 2)])
def test_multi_encoder_shapes(lead):
    enc = MultiEncoder(
        cnn_keys=["rgb"],
        mlp_keys=["state"],
        cnn_channels=(8, 16),
        cnn_kernels=(4, 4),
        cnn_strides=(2, 2),
        cnn_features_dim=32,
        mlp_hidden_sizes=(16,),
        mlp_features_dim=8,
    )
    obs = {
        "rgb": jnp.zeros((*lead, 3, 16, 16), dtype=jnp.uint8),
        "state": jnp.zeros((*lead, 10)),
    }
    params = enc.init(jax.random.PRNGKey(0), obs)
    out = enc.apply(params, obs)
    assert out.shape == (*lead, 40)


def test_multi_decoder_shapes():
    dec = MultiDecoder(
        cnn_keys=["rgb"],
        mlp_keys=["state"],
        cnn_shapes={"rgb": (3, 32, 32)},
        mlp_shapes={"state": (10,)},
        cnn_decoder_init=(4, 4, 32),
        cnn_channels=(16, 8, 3),
        cnn_kernels=(4, 4, 4),
        cnn_strides=(2, 2, 2),
        mlp_hidden_sizes=(16,),
    )
    z = jnp.zeros((5, 64))
    params = dec.init(jax.random.PRNGKey(0), z)
    out = dec.apply(params, z)
    assert out["rgb"].shape == (5, 3, 32, 32)
    assert out["state"].shape == (5, 10)
