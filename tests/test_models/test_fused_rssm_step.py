"""Fully-fused RSSM GRU step (``ops/rssm_step.py``): forward AND backward must match
the plain-XLA math — including the in-kernel matmul's weight/input gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.rssm_step import fused_gru_step, fused_step_supported, reference_gru_step


@pytest.mark.parametrize("batch,k,hidden", [(16, 96, 32), (64, 128, 64)])
def test_fused_step_forward_parity(batch, k, hidden):
    rng = np.random.default_rng(0)
    xh = jnp.asarray(rng.normal(size=(batch, k)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(batch, hidden)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(k, 3 * hidden)).astype(np.float32) * 0.05)
    gamma = jnp.asarray(rng.normal(size=(3 * hidden,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(size=(3 * hidden,)).astype(np.float32) * 0.1)

    out = fused_gru_step(xh, h, w, gamma, beta)
    ref = reference_gru_step(xh, h, w, gamma, beta)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_fused_step_gradient_parity():
    rng = np.random.default_rng(1)
    batch, k, hidden = 16, 96, 32
    xh = jnp.asarray(rng.normal(size=(batch, k)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(batch, hidden)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(k, 3 * hidden)).astype(np.float32) * 0.05)
    gamma = jnp.asarray(rng.normal(size=(3 * hidden,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(size=(3 * hidden,)).astype(np.float32) * 0.1)
    tgt = jnp.asarray(rng.normal(size=(batch, hidden)).astype(np.float32))

    def loss(fn):
        def inner(xh, h, w, gamma, beta):
            return jnp.sum((fn(xh, h, w, gamma, beta) - tgt) ** 2)

        return inner

    g_fused = jax.grad(loss(fused_gru_step), argnums=(0, 1, 2, 3, 4))(xh, h, w, gamma, beta)
    g_ref = jax.grad(loss(reference_gru_step), argnums=(0, 1, 2, 3, 4))(xh, h, w, gamma, beta)
    for name, a, b in zip(("dxh", "dh", "dw", "dgamma", "dbeta"), g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4, err_msg=name)


def test_fused_step_in_scan():
    """The consumer shape: a lax.scan over T steps carrying h — the kernel must be
    traceable/differentiable under scan like any jax op."""
    rng = np.random.default_rng(2)
    T, batch, k_in, hidden = 8, 16, 32, 32
    xs = jnp.asarray(rng.normal(size=(T, batch, k_in)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(k_in + hidden, 3 * hidden)).astype(np.float32) * 0.05)
    gamma = jnp.ones((3 * hidden,), jnp.float32)
    beta = jnp.zeros((3 * hidden,), jnp.float32)

    def rollout(fn):
        def step(h, x):
            h2 = fn(jnp.concatenate([x, h], -1), h, w, gamma, beta)
            return h2, h2

        def run(w_):
            def step_(h, x):
                h2 = fn(jnp.concatenate([x, h], -1), h, w_, gamma, beta)
                return h2, h2

            _, hs = jax.lax.scan(step_, jnp.zeros((batch, hidden)), xs)
            return jnp.sum(hs**2), hs

        return run

    (l1, hs1), g1 = jax.value_and_grad(rollout(fused_gru_step), has_aux=True)(w)
    (l2, hs2), g2 = jax.value_and_grad(rollout(reference_gru_step), has_aux=True)(w)
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=2e-4, atol=2e-4)


def test_fused_step_supported_budget():
    assert fused_step_supported(16, 1024, 512, itemsize=2)  # size S RSSM, bf16 weights
    assert not fused_step_supported(512, 4096, 4096)  # far past VMEM
