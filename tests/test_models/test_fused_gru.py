"""Fused LayerNorm-GRU Pallas kernel: forward + gradient parity vs the XLA path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models.blocks import LayerNormGRUCell
from sheeprl_tpu.ops.gru import fused_layernorm_gru, reference_layernorm_gru


@pytest.mark.parametrize("batch,hidden", [(8, 128), (16, 256), (12, 128)])
def test_fused_forward_matches_reference(batch, hidden):
    rng = np.random.default_rng(0)
    proj = jnp.asarray(rng.normal(size=(batch, 3 * hidden)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(batch, hidden)).astype(np.float32))
    gamma = jnp.asarray(rng.normal(1.0, 0.1, size=(3 * hidden,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(0.0, 0.1, size=(3 * hidden,)).astype(np.float32))
    fused = fused_layernorm_gru(proj, h, gamma, beta)
    ref = reference_layernorm_gru(proj, h, gamma, beta)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), atol=1e-5)


def test_fused_gradients_match_reference():
    rng = np.random.default_rng(1)
    batch, hidden = 8, 128
    proj = jnp.asarray(rng.normal(size=(batch, 3 * hidden)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(batch, hidden)).astype(np.float32))
    gamma = jnp.asarray(rng.normal(1.0, 0.1, size=(3 * hidden,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(0.0, 0.1, size=(3 * hidden,)).astype(np.float32))

    def loss_fused(*args):
        return jnp.sum(jnp.square(fused_layernorm_gru(*args)))

    def loss_ref(*args):
        return jnp.sum(jnp.square(reference_layernorm_gru(*args)))

    grads_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(proj, h, gamma, beta)
    grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(proj, h, gamma, beta)
    for gf, gr, name in zip(grads_fused, grads_ref, ["proj", "h", "gamma", "beta"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=2e-4, err_msg=name)


def test_cell_fused_flag_matches_xla_path(monkeypatch):
    """The cell must produce identical outputs with the kernel on and off."""
    cell = LayerNormGRUCell(hidden_size=64)
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.normal(size=(4, 64)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(4, 96)).astype(np.float32))

    monkeypatch.setenv("SHEEPRL_TPU_FUSED_GRU", "0")
    params = cell.init(jax.random.PRNGKey(0), h, x)
    out_xla, _ = cell.apply(params, h, x)
    monkeypatch.setenv("SHEEPRL_TPU_FUSED_GRU", "1")
    out_fused, _ = cell.apply(params, h, x)
    np.testing.assert_allclose(np.asarray(out_fused), np.asarray(out_xla), atol=1e-5)
