"""``-m``/``--multirun`` grid sweeps (reference: Hydra multirun via ``cli.py:358``)."""

import glob

import pytest

from sheeprl_tpu.cli import expand_multirun, run


def test_expand_multirun_grid():
    jobs = expand_multirun(["algo.lr=1e-4,3e-4", "seed=1,2", "exp=ppo"])
    assert len(jobs) == 4
    assert jobs[0] == ["algo.lr=1e-4", "seed=1", "exp=ppo"]
    assert jobs[-1] == ["algo.lr=3e-4", "seed=2", "exp=ppo"]


def test_expand_multirun_preserves_lists_and_singletons():
    # bracketed values are single values, never sweep axes
    jobs = expand_multirun(["algo.cnn_keys.encoder=[rgb,depth]", "seed=3"])
    assert jobs == [["algo.cnn_keys.encoder=[rgb,depth]", "seed=3"]]
    assert expand_multirun([]) == [[]]


@pytest.mark.slow
def test_multirun_composes_two_runs(tmp_path):
    run(
        [
            "-m",
            "exp=ppo_dummy",
            "seed=1,2",
            "dry_run=True",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.run_test=False",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "buffer.memmap=False",
            "metric.log_every=1",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            f"log_root={tmp_path}",
        ]
    )
    run_dirs = sorted(glob.glob(f"{tmp_path}/**/multirun_*/job*/version_0", recursive=True))
    assert len(run_dirs) == 2, run_dirs
    cfgs = [open(f"{d}/config.yaml").read() for d in run_dirs]
    assert "seed: 1" in cfgs[0] and "seed: 2" in cfgs[1]
