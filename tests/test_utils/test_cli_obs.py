"""CLI-level observability glue: the JAX_PLATFORMS late-init warning and obs config
validation."""

import warnings

import jax
import pytest

from sheeprl_tpu.cli import _honor_platform_env, check_configs
from sheeprl_tpu.config.core import compose


def test_honor_platform_env_warns_on_backend_mismatch(monkeypatch):
    jax.devices()  # force backend initialisation (idempotent under the test suite)
    prev = jax.config.jax_platforms
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")  # request != the live cpu backend
    try:
        with pytest.warns(UserWarning, match="already initialized"):
            _honor_platform_env()
    finally:
        jax.config.update("jax_platforms", prev)


def test_honor_platform_env_silent_when_request_already_satisfied(monkeypatch):
    jax.devices()
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")  # the live backend IS cpu: no warning
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _honor_platform_env()


def test_honor_platform_env_silent_when_unset(monkeypatch):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _honor_platform_env()


def _ppo_cfg(*overrides):
    return compose(overrides=["exp=ppo_dummy", *overrides])


def test_check_configs_accepts_valid_capture_window():
    check_configs(_ppo_cfg("obs.capture_steps=[2,5]"))
    check_configs(_ppo_cfg())  # null window


@pytest.mark.parametrize("window", ["[5,2]", "[0,3]", "[3]"])
def test_check_configs_rejects_bad_capture_window(window):
    with pytest.raises(ValueError, match="capture_steps"):
        check_configs(_ppo_cfg(f"obs.capture_steps={window}"))


def test_obs_config_group_defaults():
    cfg = _ppo_cfg()
    assert cfg.obs.enabled is False
    assert cfg.obs.trace is True
    assert cfg.obs.capture_steps is None
    assert cfg.obs.warmup_updates == 1
