"""symlog/two-hot/GAE/Ratio semantics (reference: ``tests/test_utils/test_two_hot_*.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.utils import (
    Ratio,
    gae,
    lambda_returns,
    polynomial_decay,
    symexp,
    symlog,
    two_hot_decoder,
    two_hot_encoder,
)


def test_symlog_symexp_inverse():
    x = jnp.array([-100.0, -1.0, 0.0, 0.5, 42.0])
    assert np.allclose(symexp(symlog(x)), x, atol=1e-4)


@pytest.mark.parametrize("value", [-19.7, -1.0, 0.0, 0.3, 7.77, 19.9])
def test_two_hot_roundtrip(value):
    enc = two_hot_encoder(jnp.array([value]), support_range=20, num_buckets=41)
    assert enc.shape == (41,)
    assert np.isclose(float(enc.sum()), 1.0, atol=1e-5)
    dec = two_hot_decoder(enc, support_range=20)
    assert np.isclose(float(dec[0]), value, atol=1e-4)


def test_two_hot_exact_bucket():
    enc = two_hot_encoder(jnp.array([3.0]), support_range=5, num_buckets=11)
    assert np.isclose(float(enc[8]), 1.0, atol=1e-5)
    assert np.isclose(float(enc.sum()), 1.0, atol=1e-5)


def test_two_hot_clipping():
    enc = two_hot_encoder(jnp.array([1000.0]), support_range=5, num_buckets=11)
    assert np.isclose(float(enc[-1]), 1.0, atol=1e-5)


def test_two_hot_even_buckets_raises():
    with pytest.raises(ValueError):
        two_hot_encoder(jnp.array([0.0]), support_range=5, num_buckets=10)


def test_gae_matches_reference_recursion():
    T, N = 5, 2
    rng = np.random.default_rng(0)
    rewards = rng.normal(size=(T, N, 1)).astype(np.float32)
    values = rng.normal(size=(T, N, 1)).astype(np.float32)
    dones = np.zeros((T, N, 1), dtype=np.float32)
    dones[2, 0] = 1
    next_value = rng.normal(size=(N, 1)).astype(np.float32)
    gamma, lam = 0.99, 0.95

    # straightforward python recursion
    adv_ref = np.zeros_like(rewards)
    last = np.zeros((N, 1), dtype=np.float32)
    vals_next = np.concatenate([values[1:], next_value[None]], 0)
    for t in reversed(range(T)):
        nd = 1.0 - dones[t]
        delta = rewards[t] + gamma * vals_next[t] * nd - values[t]
        last = delta + gamma * lam * nd * last
        adv_ref[t] = last

    returns, advs = jax.jit(lambda r, v, d, nv: gae(r, v, d, nv, T, gamma, lam))(
        jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(dones), jnp.asarray(next_value)
    )
    assert np.allclose(np.asarray(advs), adv_ref, atol=1e-5)
    assert np.allclose(np.asarray(returns), adv_ref + values, atol=1e-5)


def test_lambda_returns_bootstrap():
    T, B = 4, 3
    rewards = jnp.ones((T, B, 1))
    values = jnp.ones((T, B, 1)) * 2.0
    continues = jnp.ones((T, B, 1)) * 0.9
    rets = lambda_returns(rewards, values, continues, lmbda=0.95)
    assert rets.shape == (T - 1, B, 1)
    # Final step: r + c*(v*(1-l) + l*boot) with boot = values[-1]
    expected_last = 1 + 0.9 * (2.0 * 0.05 + 0.95 * 2.0)
    assert np.isclose(float(rets[-1, 0, 0]), expected_last, atol=1e-5)


def test_polynomial_decay():
    assert polynomial_decay(0, initial=1.0, final=0.0, max_decay_steps=10) == 1.0
    assert polynomial_decay(10, initial=1.0, final=0.0, max_decay_steps=10) == 0.0
    assert polynomial_decay(50, initial=1.0, final=0.0, max_decay_steps=10) == 0.0
    assert np.isclose(polynomial_decay(5, initial=1.0, final=0.0, max_decay_steps=10), 0.5)


def test_ratio_converges():
    ratio = Ratio(0.5)
    total_grad = 0
    step = 0
    for _ in range(100):
        step += 16
        total_grad += ratio(step)
    assert abs(total_grad / step - 0.5) < 0.05


def test_ratio_state_dict_roundtrip():
    r = Ratio(0.25)
    r(100)
    state = r.state_dict()
    r2 = Ratio(1.0).load_state_dict(state)
    assert r2.state_dict() == state


def test_rank_independent_aggregator_single_process():
    from sheeprl_tpu.utils.metric import RankIndependentMetricAggregator

    agg = RankIndependentMetricAggregator()
    agg.update("Loss/a", 1.0)
    agg.update("Loss/a", 3.0)
    per_rank = agg.compute_per_rank()
    assert per_rank["Loss/a"].shape == (1,)
    assert agg.compute()["Loss/a"] == 2.0
    agg.reset()
    assert agg.compute() == {}
