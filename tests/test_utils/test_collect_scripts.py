"""Guards for the LEARNING_r05 collector scripts: incomplete-run flagging and
the merge-preserving additional_runs write."""

from __future__ import annotations

import json
import os
import sys

BENCH_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "benchmarks")


def _collect_r05():
    sys.path.insert(0, os.path.abspath(BENCH_DIR))
    try:
        import collect_r05
    finally:
        sys.path.pop(0)
    return collect_r05


def test_flag_incomplete_marks_truncated_runs():
    c = _collect_r05()
    run = {
        "policy_steps": 62500,
        "train_reward_curve": [[2000, 103.97]],
        "final_test_reward": None,
        "notes": "configured for 500K env frames",
    }
    c.flag_incomplete(run)
    assert run["incomplete"] is True
    assert "RUN INCOMPLETE" in run["notes"]
    assert "2000 of 62500" in run["notes"]
    # idempotent: re-flagging does not duplicate the suffix
    notes = run["notes"]
    c.flag_incomplete(run)
    assert run["notes"] == notes


def test_flag_incomplete_leaves_complete_runs_alone():
    c = _collect_r05()
    run = {
        "policy_steps": 262144,
        "train_reward_curve": [[262144, 441.38]],
        "final_test_reward": 500.0,
        "notes": "fine",
    }
    c.flag_incomplete(run)
    assert "incomplete" not in run
    assert run["notes"] == "fine"
    # no curve and no total: nothing to compare, nothing flagged
    empty = {"policy_steps": 0, "train_reward_curve": []}
    c.flag_incomplete(empty)
    assert "incomplete" not in empty


def test_committed_learning_r05_flags_the_truncated_sac_ae_run():
    path = os.path.join(BENCH_DIR, "..", "LEARNING_r05.json")
    with open(path) as f:
        data = json.load(f)
    by_label = {r["label"]: r for r in data["additional_runs"]}
    sac_ae = by_label["sac_ae_cartpole_r5"]
    assert sac_ae.get("incomplete") is True
    assert "RUN INCOMPLETE" in sac_ae["notes"]
    # every other merged run is complete and unflagged
    for label, run in by_label.items():
        if label != "sac_ae_cartpole_r5":
            assert "incomplete" not in run, label
