"""Metric accumulators: NaN hygiene at update time, histogram percentiles, and the
aggregator's dict-flattening for histogram exports."""

import math

import numpy as np

from sheeprl_tpu.utils.metric import (
    HistogramMetric,
    MeanMetric,
    MetricAggregator,
    SumMetric,
)


def test_mean_metric_drops_nonfinite_at_update():
    m = MeanMetric()
    m.update(1.0)
    m.update(float("nan"))
    m.update(float("inf"))
    m.update(3.0)
    assert m.compute() == 2.0  # nan/inf never reached the running sum


def test_mean_metric_array_update_filters_elementwise():
    m = MeanMetric()
    m.update(np.array([1.0, np.nan, 5.0]))
    assert m.compute() == 3.0


def test_sum_metric_nan_guard():
    m = SumMetric()
    m.update([2.0, float("nan"), 4.0])
    assert m.compute() == 6.0


def test_histogram_metric_percentiles():
    h = HistogramMetric()
    h.update(list(range(1, 101)))  # 1..100
    out = h.compute()
    assert out["count"] == 100
    assert abs(out["p50"] - 50.5) < 1.0
    assert abs(out["p95"] - 95.05) < 1.0
    assert abs(out["p99"] - 99.01) < 1.0
    assert out["mean"] == 50.5
    h.reset()
    assert h.compute() is None


def test_histogram_metric_drops_nonfinite_and_caps():
    h = HistogramMetric(max_samples=4)
    h.update([1.0, float("nan")])
    for v in (2.0, 3.0, 4.0, 5.0, 6.0):
        h.update(v)
    out = h.compute()
    assert out["count"] == 6  # total observations, including overwritten ones
    # ring buffer keeps the 4 most recent values
    assert out["p99"] <= 6.0 and out["p50"] >= 3.0


def test_aggregator_flattens_histograms():
    agg = MetricAggregator({"Time/step": "histogram", "Loss/x": "mean"})
    for v in (1.0, 2.0, 3.0):
        agg.update("Time/step", v)
    agg.update("Loss/x", 0.5)
    out = agg.compute()
    assert out["Loss/x"] == 0.5
    assert out["Time/step/p50"] == 2.0
    assert out["Time/step/count"] == 3.0
    assert "Time/step" not in out  # the dict-valued metric only appears flattened


def test_aggregator_skips_empty_histogram():
    agg = MetricAggregator({"Time/idle": "histogram"})
    assert agg.compute() == {}


def test_nan_update_no_longer_poisons_window():
    # Regression: one NaN loss used to wipe the whole log window's mean.
    agg = MetricAggregator({"Loss/value_loss": "mean"})
    agg.update("Loss/value_loss", 1.0)
    agg.update("Loss/value_loss", float("nan"))
    out = agg.compute()
    assert out["Loss/value_loss"] == 1.0
    assert not math.isnan(out["Loss/value_loss"])
