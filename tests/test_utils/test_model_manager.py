"""Model registry tests (reference utils/mlflow.py:75-328 surface on the local backend)."""

import json
import threading
from pathlib import Path

from sheeprl_tpu.utils.model_manager import LocalModelManager


def _make_ckpt(tmp_path, name="ckpt_10"):
    ckpt = tmp_path / name
    ckpt.mkdir()
    (ckpt / "params.msgpack").write_bytes(b"abc")
    return ckpt


def test_register_get_transition_delete_download(tmp_path):
    mm = LocalModelManager(registry_dir=tmp_path / "registry")
    ckpt = _make_ckpt(tmp_path)

    v1 = mm.register_model(str(ckpt), "dreamer_v3_pacman", model_keys=["world_model"], metadata={"seed": 1})
    v2 = mm.register_model(str(ckpt), "dreamer_v3_pacman")
    assert (v1, v2) == (1, 2)

    models = mm.get_models()
    assert len(models["dreamer_v3_pacman"]["versions"]) == 2
    assert models["dreamer_v3_pacman"]["versions"][0]["model_keys"] == ["world_model"]

    mm.transition_model("dreamer_v3_pacman", 2, "production")
    assert mm.get_models()["dreamer_v3_pacman"]["versions"][1]["stage"] == "production"

    out = mm.download_model("dreamer_v3_pacman", 2, str(tmp_path / "dl"))
    assert (out / "params.msgpack").read_bytes() == b"abc"

    mm.delete_model("dreamer_v3_pacman", 1)
    assert len(mm.get_models()["dreamer_v3_pacman"]["versions"]) == 1
    mm.delete_model("dreamer_v3_pacman")
    assert "dreamer_v3_pacman" not in mm.get_models()


def test_registry_index_is_json(tmp_path):
    mm = LocalModelManager(registry_dir=tmp_path / "registry")
    mm.register_model(str(_make_ckpt(tmp_path)), "m")
    with open(tmp_path / "registry" / "registry.json") as f:
        idx = json.load(f)
    assert idx["m"]["versions"][0]["version"] == 1


def test_interleaved_writers_lose_no_registrations(tmp_path):
    """Two concurrent writers (own manager instances, like two processes sharing a
    filesystem registry) interleaving registrations must lose none: the index is
    locked across load→mutate→save and published via unique-temp + os.replace, so
    the final index holds every version with distinct version numbers."""
    ckpt = _make_ckpt(tmp_path)
    registry = tmp_path / "registry"
    per_writer = 10
    errors = []

    def writer(_: int) -> None:
        try:
            mm = LocalModelManager(registry_dir=registry)
            for _ in range(per_writer):
                mm.register_model(str(ckpt), "contended")
        except Exception as e:  # noqa: BLE001 - surfaced by the assert below
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert not errors
    versions = LocalModelManager(registry_dir=registry).get_models()["contended"]["versions"]
    numbers = sorted(v["version"] for v in versions)
    assert numbers == list(range(1, 2 * per_writer + 1))
    # the atomic-save path leaves no orphaned temp files behind
    assert not list(registry.glob(".registry.json.*"))
    # and the published index is valid JSON, never a torn write
    with open(registry / "registry.json") as f:
        assert len(json.load(f)["contended"]["versions"]) == 2 * per_writer


def test_register_copies_run_config_into_payload(tmp_path):
    """Registration makes the payload self-contained: the run's config.yaml
    (found at <run>/config.yaml for a <run>/checkpoints/ckpt_N source) rides
    along inside the version dir, so eval/serve can rebuild the agent from the
    registry alone."""
    run = tmp_path / "run"
    ckpt = run / "checkpoints" / "ckpt_5"
    ckpt.mkdir(parents=True)
    (ckpt / "params.msgpack").write_bytes(b"abc")
    (run / "config.yaml").write_text("algo:\n  name: ppo\n")

    mm = LocalModelManager(registry_dir=tmp_path / "registry")
    v = mm.register_model(str(ckpt), "with_cfg")
    payload = Path(mm.get_models()["with_cfg"]["versions"][v - 1]["path"])
    assert (payload / "config.yaml").read_text().startswith("algo:")
    # a payload that already carries its own config.yaml is not overwritten
    src2 = tmp_path / "payload_with_cfg"
    src2.mkdir()
    (src2 / "params.msgpack").write_bytes(b"xyz")
    (src2 / "config.yaml").write_text("algo:\n  name: sac\n")
    v2 = mm.register_model(str(src2), "with_cfg")
    payload2 = Path(mm.get_models()["with_cfg"]["versions"][v2 - 1]["path"])
    assert "sac" in (payload2 / "config.yaml").read_text()


def test_registration_cli_roundtrip(tmp_path, monkeypatch):
    """Train a tiny PPO run, then register its checkpoint via the CLI entry."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import registration, run

    run(
        [
            "exp=ppo",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "dry_run=True",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "checkpoint.every=1",
            "checkpoint.save_last=True",
            "metric.log_every=1",
            f"log_root={tmp_path}",
            "buffer.memmap=False",
        ]
    )
    ckpts = sorted(tmp_path.rglob("ckpt_*"), key=lambda p: p.stat().st_mtime)
    assert ckpts
    registration(
        [
            f"checkpoint_path={ckpts[-1]}",
            "model_manager.disabled=False",
            f"model_manager.registry_dir={tmp_path}/registry",
            "model_manager.name=ppo_test",
        ]
    )
    from sheeprl_tpu.utils.model_manager import LocalModelManager

    mm = LocalModelManager(registry_dir=tmp_path / "registry")
    assert mm.get_models()["ppo_test"]["versions"][0]["version"] == 1
