"""MemmapArray ownership/pickling (reference: ``tests/test_utils/test_memmap.py``)."""

import pickle

import numpy as np

from sheeprl_tpu.utils.memmap import MemmapArray


def test_from_array_roundtrip(tmp_path):
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    mm = MemmapArray.from_array(arr, filename=tmp_path / "a.memmap")
    assert np.allclose(mm.array, arr)
    mm[0, 0] = 99
    assert mm.array[0, 0] == 99


def test_pickle_drops_ownership(tmp_path):
    mm = MemmapArray.from_array(np.ones((2, 2)), filename=tmp_path / "b.memmap")
    clone = pickle.loads(pickle.dumps(mm))
    assert not clone.has_ownership
    assert mm.has_ownership
    assert np.allclose(clone.array, mm.array)
    # Writes through the clone are visible to the owner (same backing file).
    clone[0, 0] = 7
    assert mm.array[0, 0] == 7


def test_owner_deletes_file(tmp_path):
    path = tmp_path / "c.memmap"
    mm = MemmapArray.from_array(np.zeros(4), filename=path)
    assert path.exists()
    del mm
    assert not path.exists()


def test_from_array_same_file_does_not_steal_ownership(tmp_path):
    path = tmp_path / "d.memmap"
    mm = MemmapArray.from_array(np.zeros(4), filename=path)
    mm2 = MemmapArray.from_array(mm, filename=path)
    assert mm.has_ownership
    assert not mm2.has_ownership
