"""Distribution semantics: log-probs, straight-through gradients, two-hot."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.distributions import (
    BernoulliSafeMode,
    Categorical,
    Independent,
    MultiCategorical,
    Normal,
    OneHotCategoricalStraightThrough,
    SymlogDistribution,
    TanhNormal,
    TwoHotEncodingDistribution,
    unimix_logits,
)


def test_normal_log_prob_matches_scipy():
    from scipy.stats import norm

    d = Normal(jnp.array(1.0), jnp.array(2.0))
    assert np.isclose(float(d.log_prob(jnp.array(0.5))), norm.logpdf(0.5, 1.0, 2.0), atol=1e-5)
    assert np.isclose(float(d.entropy()), norm.entropy(1.0, 2.0), atol=1e-5)


def test_independent_reduces_event_dims():
    d = Independent(Normal(jnp.zeros((4, 3)), jnp.ones((4, 3))), 1)
    lp = d.log_prob(jnp.zeros((4, 3)))
    assert lp.shape == (4,)


def test_tanh_normal_log_prob_consistency():
    d = TanhNormal(jnp.zeros(3), jnp.ones(3))
    act, logp = d.sample_and_log_prob(jax.random.PRNGKey(0))
    assert np.all(np.abs(np.asarray(act)) <= 1.0)
    logp2 = d.log_prob(act)
    assert np.allclose(np.asarray(logp), np.asarray(logp2), atol=1e-4)


def test_categorical_log_prob():
    logits = jnp.log(jnp.array([[0.2, 0.8]]))
    d = Categorical(logits)
    assert np.isclose(float(d.log_prob(jnp.array([1]))[0]), np.log(0.8), atol=1e-5)
    assert int(d.mode[0]) == 1


def test_onehot_straight_through_gradient_flows():
    def f(logits, key):
        d = OneHotCategoricalStraightThrough(logits)
        return (d.rsample(key) * jnp.arange(4.0)).sum()

    g = jax.grad(f)(jnp.zeros(4), jax.random.PRNGKey(0))
    assert np.any(np.asarray(g) != 0)  # gradient flows through probs


def test_unimix_mixes_uniform():
    logits = jnp.array([100.0, 0.0, 0.0, 0.0])
    mixed = unimix_logits(logits, unimix=0.01)
    probs = np.asarray(jax.nn.softmax(mixed))
    assert probs.min() > 0.002  # uniform floor present


def test_two_hot_distribution_mean_inverts_symlog():
    bins = 255
    target = 7.3
    from sheeprl_tpu.utils.utils import symlog, two_hot_encoder

    enc = two_hot_encoder(symlog(jnp.array([target])), support_range=20, num_buckets=bins)
    # logits == log target distribution → mean must decode back
    d = TwoHotEncodingDistribution(jnp.log(enc + 1e-8))
    assert np.isclose(float(d.mean[0]), target, atol=0.05)


def test_two_hot_log_prob_maximised_at_target():
    logits = jnp.zeros((1, 255))
    d = TwoHotEncodingDistribution(logits)
    lp = d.log_prob(jnp.array([[3.0]]))
    assert lp.shape == (1, 1)


def test_symlog_distribution_mode():
    d = SymlogDistribution(jnp.array([[0.0, 1.0]]), dims=1)
    assert np.allclose(np.asarray(d.mode), np.asarray([[0.0, np.e - 1]]), atol=1e-5)
    lp = d.log_prob(jnp.array([[0.0, np.e - 1]]))
    assert np.isclose(float(lp[0]), 0.0, atol=1e-5)


def test_bernoulli_safe_mode():
    d = BernoulliSafeMode(jnp.zeros(3))
    assert np.allclose(np.asarray(d.mode), 0)
    d = BernoulliSafeMode(jnp.ones(3))
    assert np.allclose(np.asarray(d.mode), 1)


def test_multi_categorical():
    logits = jnp.log(jnp.array([0.1, 0.9, 0.5, 0.5]))[None]
    d = MultiCategorical(logits, nvec=[2, 2])
    lp = d.log_prob(jnp.array([[1, 0]]))
    assert np.isclose(float(lp[0]), np.log(0.9) + np.log(0.5), atol=1e-5)
    assert d.mode.shape == (1, 2)
