"""Persistent XLA compilation cache (``compile_cache.{enabled,dir}`` wired in
``cli.run_algorithm`` — the first slice of ROADMAP item 3's cold-start story).

The warm-vs-cold contract: the first run populates the cache directory with one
serialized executable per compiled program; a second identical run compiles
NOTHING new (every program deserializes), observed as a stable cache-file count.
The wall-clock half of the story is the ``anakin_compile_seconds`` BENCH row
(``benchmarks/anakin_bench.py --compile-bench 1``, two fresh subprocesses).
"""

import json

import pytest

from sheeprl_tpu.cli import run

TINY_ANAKIN = [
    "exp=ppo",
    "env=jax_cartpole",
    "algo.anakin=True",
    "algo.mlp_keys.encoder=[state]",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.encoder.mlp_features_dim=8",
    "algo.total_steps=8",
    "algo.run_test=False",
    "env.num_envs=2",
    "env.capture_video=False",
    "dry_run=True",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "metric.log_every=1",
    "buffer.memmap=False",
]


def _cache_files(cache_dir):
    return sorted(p for p in cache_dir.rglob("*") if p.is_file())


def test_compile_cache_cold_then_warm(tmp_path):
    cache_dir = tmp_path / "xla_cache"
    args = TINY_ANAKIN + [
        "compile_cache.enabled=True",
        f"compile_cache.dir={cache_dir}",
    ]
    run(args + [f"log_root={tmp_path / 'run1'}"])
    cold_files = _cache_files(cache_dir)
    assert cold_files, "first (cold) run wrote no cache entries"

    # warm run: every program deserializes — the cache gains nothing new
    run(args + [f"log_root={tmp_path / 'run2'}"])
    warm_files = _cache_files(cache_dir)
    assert [p.name for p in warm_files] == [p.name for p in cold_files], (
        "second run recompiled programs the cache should have served"
    )


def test_compile_cache_disabled_leaves_dir_empty(tmp_path):
    cache_dir = tmp_path / "xla_cache_off"
    run(TINY_ANAKIN + [f"compile_cache.dir={cache_dir}", f"log_root={tmp_path / 'run'}"])
    assert not cache_dir.exists(), "compile_cache.enabled=False must not touch the cache dir"


@pytest.mark.slow
def test_compile_bench_warm_beats_cold():
    """The BENCH row's claim end to end: a fresh process with a warm persistent
    cache reaches its first fused dispatch faster than the cold process that
    filled it (subprocess-heavy — slow tier)."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "benchmarks"))
    try:
        import anakin_bench
    finally:
        sys.path.pop(0)
    res = anakin_bench.bench_compile_cache(num_envs=2, rollout_steps=4)
    assert res["cold_seconds"] > 0 and res["warm_seconds"] > 0
    assert res["warm_seconds"] < res["cold_seconds"], (
        f"warm start ({res['warm_seconds']:.2f}s) did not beat cold ({res['cold_seconds']:.2f}s)"
    )


@pytest.mark.slow
def test_compile_bench_row_shape(capsys):
    """Slow tier (2 subprocess probes): `--compile-bench 1` emits the
    anakin_compile_seconds row (the other rows are covered by
    test_anakin_bench_smoke; the cache behavior itself by the tests above)."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "benchmarks"))
    try:
        import anakin_bench
    finally:
        sys.path.pop(0)
    anakin_bench.main(
        ["--num-envs", "4", "--steps", "16", "--host-steps", "8", "--rollout-steps", "4",
         "--ppo-envs", "2", "--iters", "1", "--host-envs", "2", "--skip-population",
         "--pop-envs", "2", "--pop-rollout", "4", "--compile-bench", "1"]
    )
    rows = [json.loads(line) for line in capsys.readouterr().out.splitlines() if line.strip()]
    by_metric = {r["metric"]: r for r in rows}
    row = by_metric["anakin_compile_seconds"]
    assert row["value"] > 0 and row["cold_seconds"] > 0 and row["warm_speedup"] > 0
