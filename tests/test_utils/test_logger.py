"""Logger glue tests — versioned run dir + TB fallback + the MLflow option
(reference ``utils/logger.py:12-36`` + ``configs/logger/mlflow.yaml``)."""

import sys
import types

import pytest

from sheeprl_tpu.config.core import compose
from sheeprl_tpu.utils.logger import MlflowLogger, TensorBoardLogger, get_logger


def _stub_mlflow(monkeypatch):
    calls = {"metrics": [], "params": [], "ended": []}
    stub = types.ModuleType("mlflow")

    class _Info:
        run_id = "run-123"

    class _Run:
        info = _Info()

    stub.set_tracking_uri = lambda uri: calls.setdefault("uri", uri)
    stub.set_experiment = lambda name: calls.setdefault("experiment", name)
    def _start_run(run_id=None, run_name=None):
        calls["run_name"] = run_name
        return _Run()

    stub.start_run = _start_run
    stub.log_metrics = lambda m, step=None: calls["metrics"].append((m, step))
    stub.log_params = lambda p: calls["params"].append(p)
    stub.end_run = lambda: calls["ended"].append(True)
    monkeypatch.setitem(sys.modules, "mlflow", stub)
    monkeypatch.setattr("sheeprl_tpu.utils.imports._IS_MLFLOW_AVAILABLE", True)
    return calls


def test_mlflow_logger_selected_and_logs(tmp_path, monkeypatch):
    calls = _stub_mlflow(monkeypatch)
    cfg = compose(overrides=["exp=ppo_dummy", "logger=mlflow", "exp_name=myexp", "run_name=r1"])
    assert cfg.logger.name == "mlflow"
    assert cfg.logger.experiment_name == "myexp"
    logger = get_logger(cfg, str(tmp_path))
    assert isinstance(logger, MlflowLogger)
    assert logger.run_id == "run-123"
    assert calls["experiment"] == "myexp"
    logger.log_metrics({"Loss/policy_loss": 1.5}, step=10)
    logger.log_hyperparams({"algo": {"name": "ppo"}})
    logger.close()
    assert calls["metrics"] == [({"Loss/policy_loss": 1.5}, 10)]
    assert calls["params"] == [{"algo.name": "ppo"}]
    assert calls["ended"] == [True]


def test_mlflow_logger_missing_package_errors(tmp_path, monkeypatch):
    monkeypatch.setattr("sheeprl_tpu.utils.imports._IS_MLFLOW_AVAILABLE", False)
    cfg = compose(overrides=["exp=ppo_dummy", "logger=mlflow"])
    with pytest.raises(ModuleNotFoundError, match="mlflow"):
        get_logger(cfg, str(tmp_path))


def test_default_logger_is_tensorboard(tmp_path):
    cfg = compose(overrides=["exp=ppo_dummy"])
    assert cfg.logger.name == "tensorboard"
    logger = get_logger(cfg, str(tmp_path))
    assert isinstance(logger, TensorBoardLogger)
    logger.log_metrics({"a": 1.0}, step=1)
    logger.close()


def test_log_level_zero_disables_logger(tmp_path):
    cfg = compose(overrides=["exp=ppo_dummy", "metric.log_level=0"])
    assert get_logger(cfg, str(tmp_path)) is None


def _jsonl_logger(tmp_path, monkeypatch):
    """Force the JSONL fallback by making both SummaryWriter imports fail."""
    monkeypatch.setitem(sys.modules, "tensorboardX", None)
    monkeypatch.setitem(sys.modules, "torch.utils.tensorboard", None)
    return TensorBoardLogger(str(tmp_path))


def test_jsonl_fallback_close_releases_handle(tmp_path, monkeypatch):
    logger = _jsonl_logger(tmp_path, monkeypatch)
    assert logger._writer is None and logger._jsonl is not None
    logger.log_metrics({"a": 1.0}, step=1)
    handle = logger._jsonl
    logger.close()
    assert logger._jsonl is None and handle.closed  # the fd used to leak


def test_log_metrics_after_close_is_noop(tmp_path, monkeypatch):
    logger = _jsonl_logger(tmp_path, monkeypatch)
    logger.log_metrics({"a": 1.0}, step=1)
    logger.close()
    logger.log_metrics({"b": 2.0}, step=2)  # must not raise on the closed handle
    lines = (tmp_path / "metrics.jsonl").read_text().strip().splitlines()
    assert len(lines) == 1
    logger.close()  # idempotent
