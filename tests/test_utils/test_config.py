"""Config composition engine tests."""

import pytest

from sheeprl_tpu.config.core import DotDict, compose


def test_compose_exp_preset():
    cfg = compose(overrides=["exp=ppo_dummy"])
    assert cfg.algo.name == "ppo"
    assert cfg.env.id == "discrete_dummy"
    assert cfg.algo.mlp_keys.encoder == ["state"]


def test_group_and_value_overrides():
    cfg = compose(overrides=["exp=ppo", "env=dummy", "algo.rollout_steps=7", "seed=9"])
    assert cfg.env.id == "discrete_dummy"
    assert cfg.algo.rollout_steps == 7
    assert cfg.seed == 9


def test_interpolation_resolution():
    cfg = compose(overrides=["exp=ppo_dummy"])
    assert cfg.exp_name == "ppo_discrete_dummy"
    assert cfg.buffer.size == cfg.algo.rollout_steps
    assert cfg.algo.encoder.dense_act == cfg.algo.dense_act


def test_scientific_notation_parses_as_float():
    cfg = compose(overrides=["exp=ppo_dummy", "algo.optimizer.lr=3e-4"])
    assert isinstance(cfg.algo.optimizer.lr, float)
    assert cfg.algo.optimizer.lr == pytest.approx(3e-4)


def test_missing_mandatory_group_raises():
    with pytest.raises(ValueError, match="Mandatory"):
        compose(overrides=[])


def test_unknown_group_option_raises():
    with pytest.raises(FileNotFoundError, match="Available"):
        compose(overrides=["exp=ppo_dummy", "env=does_not_exist"])


def test_search_path_extension(tmp_path, monkeypatch):
    exp_dir = tmp_path / "exp"
    exp_dir.mkdir()
    (exp_dir / "custom.yaml").write_text("defaults:\n  - ppo_dummy\nseed: 123\n")
    monkeypatch.setenv("SHEEPRL_TPU_SEARCH_PATH", str(tmp_path))
    cfg = compose(overrides=["exp=custom"])
    assert cfg.seed == 123
    assert cfg.algo.name == "ppo"


def test_dotdict_attribute_access():
    d = DotDict.wrap({"a": {"b": 1}})
    assert d.a.b == 1
    d.a.c = 2
    assert d["a"]["c"] == 2
