"""Config composition engine tests."""

import pytest

from sheeprl_tpu.config.core import DotDict, compose


def test_compose_exp_preset():
    cfg = compose(overrides=["exp=ppo_dummy"])
    assert cfg.algo.name == "ppo"
    assert cfg.env.id == "discrete_dummy"
    assert cfg.algo.mlp_keys.encoder == ["state"]


def test_group_and_value_overrides():
    cfg = compose(overrides=["exp=ppo", "env=dummy", "algo.rollout_steps=7", "seed=9"])
    assert cfg.env.id == "discrete_dummy"
    assert cfg.algo.rollout_steps == 7
    assert cfg.seed == 9


def test_interpolation_resolution():
    cfg = compose(overrides=["exp=ppo_dummy"])
    assert cfg.exp_name == "ppo_discrete_dummy"
    assert cfg.buffer.size == cfg.algo.rollout_steps
    assert cfg.algo.encoder.dense_act == cfg.algo.dense_act


def test_scientific_notation_parses_as_float():
    cfg = compose(overrides=["exp=ppo_dummy", "algo.optimizer.lr=3e-4"])
    assert isinstance(cfg.algo.optimizer.lr, float)
    assert cfg.algo.optimizer.lr == pytest.approx(3e-4)


def test_missing_mandatory_group_raises():
    with pytest.raises(ValueError, match="Mandatory"):
        compose(overrides=[])


def test_unknown_group_option_raises():
    with pytest.raises(FileNotFoundError, match="Available"):
        compose(overrides=["exp=ppo_dummy", "env=does_not_exist"])


def test_search_path_extension(tmp_path, monkeypatch):
    exp_dir = tmp_path / "exp"
    exp_dir.mkdir()
    (exp_dir / "custom.yaml").write_text("defaults:\n  - ppo_dummy\nseed: 123\n")
    monkeypatch.setenv("SHEEPRL_TPU_SEARCH_PATH", str(tmp_path))
    cfg = compose(overrides=["exp=custom"])
    assert cfg.seed == 123
    assert cfg.algo.name == "ppo"


def test_dotdict_attribute_access():
    d = DotDict.wrap({"a": {"b": 1}})
    assert d.a.b == 1
    d.a.c = 2
    assert d["a"]["c"] == 2


def test_every_exp_preset_composes():
    """Every shipped exp preset must compose without errors (the reference's whole
    config tree is usable out of the box; a broken preset is a silent capability gap).
    Finetuning presets require the exploration checkpoint path, like the reference."""
    import pathlib

    import sheeprl_tpu.config.core as core

    from sheeprl_tpu.cli import _import_algorithms, check_configs

    _import_algorithms()
    exp_dir = pathlib.Path(core.__file__).parent / "configs" / "exp"
    names = sorted(p.stem for p in exp_dir.glob("*.yaml"))
    assert len(names) >= 49
    for name in names:
        overrides = [f"exp={name}"]
        if "finetuning" in name or "fntn" in name:
            overrides.append("checkpoint.exploration_ckpt_path=/tmp/ckpt")
        cfg = compose(overrides=overrides)
        assert cfg.algo.name, name
        check_configs(cfg)  # incl. the prefill-vs-sequence-length guard


def test_override_prefix_requires_separator(tmp_path, monkeypatch):
    """A group whose name merely begins with 'override' is a plain group selection,
    never truncated; only 'override <group>' / 'override/<group>' keys are overrides."""
    group_dir = tmp_path / "overriders"
    group_dir.mkdir()
    (group_dir / "a.yaml").write_text("x: 1\n")
    exp_dir = tmp_path / "exp"
    exp_dir.mkdir()
    (exp_dir / "custom.yaml").write_text("defaults:\n  - ppo_dummy\n  - overriders: a\n")
    monkeypatch.setenv("SHEEPRL_TPU_SEARCH_PATH", str(tmp_path))
    cfg = compose(overrides=["exp=custom"])
    assert cfg.overriders.x == 1


def test_mixed_defaults_entry_classified_per_key(tmp_path, monkeypatch):
    """A dict defaults entry mixing an override key with a plain group key keeps the
    plain key intact (not mangled to the empty group)."""
    exp_dir = tmp_path / "exp"
    exp_dir.mkdir()
    (exp_dir / "custom.yaml").write_text(
        "defaults:\n  - {override /algo: ppo, env: dummy}\n"
        "seed: 5\nbuffer:\n  size: 64\nalgo:\n  total_steps: 64\n  per_rank_batch_size: 4\n"
    )
    monkeypatch.setenv("SHEEPRL_TPU_SEARCH_PATH", str(tmp_path))
    cfg = compose(overrides=["exp=custom"])
    assert cfg.algo.name == "ppo"
    assert cfg.env.id == "discrete_dummy"
    assert cfg.seed == 5


def test_unmatched_override_raises(tmp_path, monkeypatch):
    """An override targeting a group that exists nowhere in the defaults tree errors
    (Hydra: 'could not find match for override') instead of silently loading last."""
    exp_dir = tmp_path / "exp"
    exp_dir.mkdir()
    (exp_dir / "custom.yaml").write_text("defaults:\n  - ppo_dummy\n  - override /enviro: dummy\n")
    monkeypatch.setenv("SHEEPRL_TPU_SEARCH_PATH", str(tmp_path))
    with pytest.raises(ValueError, match="matches no 'enviro' entry"):
        compose(overrides=["exp=custom"])


def test_exp_inheriting_exp_keeps_concrete_values():
    """``override /algo:`` in a child exp re-selects the option the parent exp's
    defaults load — it must NOT re-merge the algo group file after the parent exp's
    content, which would clobber the parent's concrete values (batch size, obs keys)
    with the group file's defaults (Hydra defaults-list semantics)."""
    cfg = compose(overrides=["exp=dreamer_v3_100k_ms_pacman"])
    assert cfg.algo.per_rank_batch_size == 16  # from exp dreamer_v3
    assert cfg.algo.cnn_keys.encoder == ["rgb"]  # from exp dreamer_v3
    assert cfg.algo.world_model.recurrent_model.recurrent_state_size == 512  # S size
    assert cfg.algo.replay_ratio == 1  # reference exp/dreamer_v3.yaml:11
    # CLI group selections still beat the child exp's override entries.
    cfg = compose(overrides=["exp=dreamer_v3_100k_ms_pacman", "algo=dreamer_v3_M"])
    assert cfg.algo.world_model.recurrent_model.recurrent_state_size == 1024  # M size
    assert cfg.algo.per_rank_batch_size == 16


def test_dv1_dv2_pixel_geometry_validated_not_mutated():
    """DV1/DV2 pixel presets require screen_size=64/frame_stack<=1; the CLI validates
    instead of silently overwriting, so the saved config never contradicts the user."""
    from sheeprl_tpu.cli import _import_algorithms, check_configs

    _import_algorithms()
    for exp in ("dreamer_v1_dummy", "dreamer_v2_dummy"):
        check_configs(compose(overrides=[f"exp={exp}"]))  # shipped presets pass
        with pytest.raises(ValueError, match="screen_size"):
            check_configs(compose(overrides=[f"exp={exp}", "env.screen_size=128"]))
