"""Pure-JAX env family (``sheeprl_tpu/envs/jax``): trajectory parity against the
gymnasium counterparts from IDENTICAL physics state (the ISSUE-6 correctness
contract), auto-reset semantics, the host gym adapter, and the registry."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.envs.jax import make_jax_env
from sheeprl_tpu.envs.jax.cartpole import CartPoleState
from sheeprl_tpu.envs.jax.mountain_car import MountainCarState
from sheeprl_tpu.envs.jax.pendulum import PendulumState


def _cartpole_state(genv):
    s = genv.unwrapped.state
    return CartPoleState(
        jnp.float32(s[0]), jnp.float32(s[1]), jnp.float32(s[2]), jnp.float32(s[3]), jnp.int32(0)
    )


def _pendulum_state(genv):
    th, thd = genv.unwrapped.state
    return PendulumState(jnp.float32(th), jnp.float32(thd), jnp.int32(0))


def _mcc_state(genv):
    p, v = genv.unwrapped.state
    return MountainCarState(jnp.float32(p), jnp.float32(v), jnp.int32(0))


def _parity_rollout(jax_id, gym_id, state_fn, action_fn, steps, atol):
    """Step both implementations from the same physics state with the same action
    sequence; assert matching obs/reward/termination trajectories."""
    env = make_jax_env(jax_id)
    params = env.default_params()
    genv = gym.make(gym_id)
    genv.reset(seed=0)
    state = state_fn(genv)
    step = jax.jit(env.step)
    key = jax.random.PRNGKey(0)
    rng = np.random.default_rng(1)
    n = 0
    for t in range(steps):
        a = action_fn(rng)
        gobs, grew, gterm, gtrunc, _ = genv.step(a)
        state, obs, rew, done, info = step(params, state, jnp.asarray(a), key)
        np.testing.assert_allclose(np.asarray(obs), gobs, atol=atol, err_msg=f"obs diverged at step {t}")
        assert abs(float(rew) - float(grew)) <= atol, (t, float(rew), grew)
        assert bool(info["terminated"]) == gterm, f"termination diverged at step {t}"
        assert bool(info["truncated"]) == gtrunc, f"truncation diverged at step {t}"
        n += 1
        if gterm or gtrunc:
            break
    assert n > 5, "trajectory too short to be meaningful"


def test_cartpole_parity_vs_gymnasium():
    # fp32 vs gymnasium's fp64: identical dynamics, drift < 1e-5 over an episode
    _parity_rollout(
        "jax_cartpole", "CartPole-v1", _cartpole_state, lambda rng: int(rng.integers(0, 2)), 500, 1e-4
    )


def test_pendulum_parity_vs_gymnasium():
    _parity_rollout(
        "jax_pendulum",
        "Pendulum-v1",
        _pendulum_state,
        lambda rng: rng.uniform(-2, 2, (1,)).astype(np.float32),
        50,
        1e-3,
    )


def test_mountain_car_parity_vs_gymnasium():
    _parity_rollout(
        "jax_mountain_car",
        "MountainCarContinuous-v0",
        _mcc_state,
        lambda rng: rng.uniform(-1, 1, (1,)).astype(np.float32),
        200,
        1e-4,
    )


def test_cartpole_reset_distribution_bounds():
    """Reset-distribution equivalence (documented contract): uniform in
    [-0.05, 0.05]^4 like gymnasium — bounds + coverage sanity over many draws."""
    env = make_jax_env("cartpole")
    params = env.default_params()
    keys = jax.random.split(jax.random.PRNGKey(0), 512)
    _states, obs = jax.vmap(env.reset, in_axes=(None, 0))(params, keys)
    arr = np.asarray(obs)
    assert arr.shape == (512, 4)
    assert (np.abs(arr) <= 0.05 + 1e-7).all()
    assert np.abs(arr).max() > 0.04  # actually fills the range
    assert np.abs(arr.mean()) < 0.01


def test_autoreset_resets_state_and_keeps_final_obs():
    env = make_jax_env("cartpole")
    params = env.default_params()
    # A state past the termination threshold: the NEXT step terminates.
    state = CartPoleState(
        jnp.float32(3.0), jnp.float32(1.0), jnp.float32(0.0), jnp.float32(0.0), jnp.int32(7)
    )
    new_state, obs, reward, done, info = jax.jit(env.step_autoreset)(
        params, state, jnp.int32(1), jax.random.PRNGKey(0)
    )
    assert bool(done) and bool(info["terminated"])
    assert float(reward) == 1.0  # the terminating step still pays out
    assert int(new_state.time) == 0  # fresh episode
    assert (np.abs(np.asarray(obs)) <= 0.05 + 1e-7).all()  # reset obs, not the crashed one
    assert abs(float(info["final_obs"][0]) - 3.02) < 1e-5  # true pre-reset obs (x + tau*x_dot)


def test_time_limit_truncates_pendulum():
    env = make_jax_env("pendulum")
    params = env.default_params()._replace(max_episode_steps=3)
    state, _ = env.reset(params, jax.random.PRNGKey(0))
    step = jax.jit(env.step)
    key = jax.random.PRNGKey(1)
    for t in range(3):
        state, _obs, _r, done, info = step(params, state, jnp.zeros((1,), jnp.float32), key)
    assert bool(done) and bool(info["truncated"]) and not bool(info["terminated"])


def test_sample_action_bounds():
    for env_id, check in (
        ("cartpole", lambda a: a.dtype == np.int32 and set(np.unique(a)) <= {0, 1}),
        ("pendulum", lambda a: a.shape[-1] == 1 and (np.abs(a) <= 2.0).all()),
    ):
        env = make_jax_env(env_id)
        params = env.default_params()
        keys = jax.random.split(jax.random.PRNGKey(0), 64)
        acts = np.asarray(jax.vmap(env.sample_action, in_axes=(None, 0))(params, keys))
        assert check(acts), env_id


def test_registry_ids_and_errors():
    assert make_jax_env("cartpole").name == "cartpole"
    assert make_jax_env("jax_mountain_car").name == "mountain_car_continuous"
    with pytest.raises(ValueError, match="Unknown jax env"):
        make_jax_env("not_an_env")


def test_gym_adapter_through_sync_vector_env():
    """The host-compat wrapper: same dynamics through the ordinary gymnasium
    vector path (what ``env=jax_cartpole`` runs WITHOUT algo.anakin)."""
    from sheeprl_tpu.envs.jax.gym_adapter import JaxToGymEnv

    envs = gym.vector.SyncVectorEnv(
        [lambda i=i: JaxToGymEnv("cartpole", seed=i) for i in range(2)],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    obs, _ = envs.reset(seed=3)
    assert obs.shape == (2, 4) and (np.abs(obs) <= 0.05 + 1e-7).all()
    done_seen = False
    for _ in range(600):  # the 500-step TimeLimit guarantees an episode end
        obs, rew, term, trunc, info = envs.step(np.array([1, 0]))
        assert obs.shape == (2, 4) and rew.shape == (2,)
        if term.any() or trunc.any():
            done_seen = True
            break
    assert done_seen
    envs.close()


def test_gym_adapter_seeding_is_deterministic():
    from sheeprl_tpu.envs.jax.gym_adapter import JaxToGymEnv

    a, b = JaxToGymEnv("pendulum"), JaxToGymEnv("pendulum")
    oa, _ = a.reset(seed=5)
    ob, _ = b.reset(seed=5)
    np.testing.assert_array_equal(oa, ob)


def test_gymnax_adapter_roundtrip():
    pytest.importorskip("gymnax", reason="optional gymnax not installed")
    env = make_jax_env("gymnax:CartPole-v1")
    params = env.default_params()
    state, obs = env.reset(params, jax.random.PRNGKey(0))
    assert np.asarray(obs).shape == env.observation_space(params).shape
    state, obs, rew, done, info = jax.jit(env.step)(params, state, jnp.int32(1), jax.random.PRNGKey(1))
    assert "terminated" in info and np.asarray(obs).shape == (4,)


def test_gymnax_adapter_missing_dependency_message():
    try:
        import gymnax  # noqa: F401

        pytest.skip("gymnax installed; the missing-dep path is not reachable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="gymnax"):
        make_jax_env("gymnax:CartPole-v1")
