"""DMC adapter + shipped DMC presets actually instantiate (the preset-composition
test alone missed wrapper kwargs that DMCWrapper does not accept).

Runs in a SUBPROCESS: MuJoCo's EGL renderer segfaults in any process that has
loaded a TensorFlow runtime, and earlier suite tests import
``tensorboard.backend...EventAccumulator`` (see utils/logger.py for the same
issue on the training side, solved by preferring tensorboardX).
"""

import functools
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

pytest.importorskip("dm_control")

REPO = Path(__file__).resolve().parents[2]


@functools.lru_cache(maxsize=1)
def no_egl() -> bool:
    """Runtime capability probe: can this container actually create an EGL GL
    context?  dm_control being importable says nothing about the render stack —
    headless CI images routinely ship MuJoCo without a GPU/EGL driver, and the
    render call then aborts the whole process.  Probe in a SUBPROCESS (same
    reason the tests themselves run in one) so a segfaulting EGL stack reads as
    "no EGL" instead of killing the pytest runner."""
    probe = (
        "import os; os.environ['MUJOCO_GL'] = 'egl';"
        "import mujoco; mujoco.GLContext(32, 32); print('egl-ok')"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True, timeout=120
        )
    except (subprocess.TimeoutExpired, OSError):
        return True
    return proc.returncode != 0 or "egl-ok" not in proc.stdout

CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ.setdefault("MUJOCO_GL", "egl")
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["SHEEPRL_TPU_QUIET"] = "1"
    sys.path.insert(0, {repo!r})
    import numpy as np
    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.utils.env import make_env

    exp = sys.argv[1]
    cfg = compose(overrides=[f"exp={{exp}}", "env.capture_video=False"])
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (3, cfg.env.screen_size, cfg.env.screen_size), obs["rgb"].shape
    assert obs["rgb"].dtype == np.uint8
    obs, reward, term, trunc, _ = env.step(env.action_space.sample())
    assert np.isfinite(reward)
    env.close()
    print(f"dmc {{exp}} OK", flush=True)
    """
).format(repo=str(REPO))


@pytest.mark.skipif(no_egl(), reason="no EGL render stack in this container (capability probe)")
@pytest.mark.parametrize("exp", ["dreamer_v3_dmc_walker_walk", "dreamer_v3_dmc_cartpole_swingup_sparse"])
def test_dmc_preset_env_instantiates(tmp_path, exp):
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    proc = subprocess.run(
        [sys.executable, str(script), exp], capture_output=True, text=True, timeout=300
    )
    assert proc.returncode == 0, f"{exp} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    assert f"dmc {exp} OK" in proc.stdout
