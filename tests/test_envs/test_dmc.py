"""DMC adapter + shipped DMC presets actually instantiate (the preset-composition
test alone missed wrapper kwargs that DMCWrapper does not accept).

Runs in a SUBPROCESS: MuJoCo's EGL renderer segfaults in any process that has
loaded a TensorFlow runtime, and earlier suite tests import
``tensorboard.backend...EventAccumulator`` (see utils/logger.py for the same
issue on the training side, solved by preferring tensorboardX).
"""

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

pytest.importorskip("dm_control")

REPO = Path(__file__).resolve().parents[2]

CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ.setdefault("MUJOCO_GL", "egl")
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["SHEEPRL_TPU_QUIET"] = "1"
    sys.path.insert(0, {repo!r})
    import numpy as np
    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.utils.env import make_env

    exp = sys.argv[1]
    cfg = compose(overrides=[f"exp={{exp}}", "env.capture_video=False"])
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (3, cfg.env.screen_size, cfg.env.screen_size), obs["rgb"].shape
    assert obs["rgb"].dtype == np.uint8
    obs, reward, term, trunc, _ = env.step(env.action_space.sample())
    assert np.isfinite(reward)
    env.close()
    print(f"dmc {{exp}} OK", flush=True)
    """
).format(repo=str(REPO))


@pytest.mark.parametrize("exp", ["dreamer_v3_dmc_walker_walk", "dreamer_v3_dmc_cartpole_swingup_sparse"])
def test_dmc_preset_env_instantiates(tmp_path, exp):
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    proc = subprocess.run(
        [sys.executable, str(script), exp], capture_output=True, text=True, timeout=300
    )
    assert proc.returncode == 0, f"{exp} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    assert f"dmc {exp} OK" in proc.stdout
