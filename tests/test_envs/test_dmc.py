"""DMC adapter + shipped DMC presets actually instantiate (the preset-composition
test alone missed wrapper kwargs that DMCWrapper does not accept)."""

import os

import numpy as np
import pytest

dm_control = pytest.importorskip("dm_control")
os.environ.setdefault("MUJOCO_GL", "egl")


@pytest.mark.parametrize("exp", ["dreamer_v3_dmc_walker_walk", "dreamer_v3_dmc_cartpole_swingup_sparse"])
def test_dmc_preset_env_instantiates(exp):
    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.utils.env import make_env

    cfg = compose(overrides=[f"exp={exp}", "env.capture_video=False"])
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (3, cfg.env.screen_size, cfg.env.screen_size)
    assert obs["rgb"].dtype == np.uint8
    obs, reward, term, trunc, _ = env.step(env.action_space.sample())
    assert np.isfinite(reward)
    env.close()
