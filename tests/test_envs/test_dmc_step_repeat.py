"""DMC ``step_repeat`` fast path (the ActionRepeat adapter protocol): one render per
repeated step instead of one per physics step, with EXACTLY the generic loop's
trajectory — same physics, same summed rewards, same surviving observation."""

import numpy as np
import pytest

from sheeprl_tpu.utils.imports import _IS_DMC_AVAILABLE

pytestmark = pytest.mark.skipif(not _IS_DMC_AVAILABLE, reason="dm_control not installed")


def _rollout(use_native: bool, steps: int = 10):
    from sheeprl_tpu.envs.dmc import DMCWrapper
    from sheeprl_tpu.envs.wrappers import ActionRepeat

    env = DMCWrapper("cartpole_balance", seed=3, from_pixels=False, from_vectors=True)
    ar = ActionRepeat(env, 2)
    if not use_native:
        ar._native = None  # force the generic repeat loop
    obs, _ = ar.reset()
    rng = np.random.default_rng(0)
    rewards, states = [], []
    for _ in range(steps):
        action = rng.uniform(-1, 1, env.action_space.shape).astype(np.float32)
        obs, reward, terminated, truncated, _ = ar.step(action)
        rewards.append(reward)
        states.append(obs["state"].copy())
    return np.asarray(rewards), np.stack(states)


def test_step_repeat_matches_generic_loop():
    r_generic, s_generic = _rollout(use_native=False)
    r_native, s_native = _rollout(use_native=True)
    np.testing.assert_allclose(r_native, r_generic, rtol=0, atol=0)
    np.testing.assert_array_equal(s_native, s_generic)


def test_action_repeat_binds_fast_path():
    from sheeprl_tpu.envs.dmc import DMCWrapper
    from sheeprl_tpu.envs.wrappers import ActionRepeat

    import gymnasium as gym

    env = DMCWrapper("cartpole_balance", seed=0, from_pixels=False, from_vectors=True)
    assert ActionRepeat(env, 2)._native is not None

    # no step_repeat -> generic loop
    assert ActionRepeat(gym.make("CartPole-v1"), 2)._native is None

    # an intermediate wrapper means the fast path would skip its step(): unbound
    assert ActionRepeat(gym.wrappers.TransformReward(env, lambda r: r), 2)._native is None
