"""Env wrapper + make_env pipeline tests (reference: ``tests/test_envs/``)."""

import numpy as np
import pytest

from sheeprl_tpu.config.core import compose
from sheeprl_tpu.envs.dummy import ContinuousDummyEnv, DiscreteDummyEnv, MultiDiscreteDummyEnv
from sheeprl_tpu.envs.wrappers import ActionRepeat, ActionsAsObservationWrapper, FrameStack, RewardAsObservationWrapper
from sheeprl_tpu.utils.env import make_env


def test_dummy_env_contract():
    env = DiscreteDummyEnv(n_steps=4)
    obs, _ = env.reset()
    assert set(obs.keys()) == {"rgb", "state"}
    assert obs["rgb"].shape == (3, 64, 64)
    done = False
    steps = 0
    while not done:
        obs, r, term, trunc, _ = env.step(env.action_space.sample())
        done = term or trunc
        steps += 1
    assert steps == 5


def test_action_repeat_accumulates_reward():
    class RewEnv(DiscreteDummyEnv):
        def step(self, action):
            obs, _, d, t, i = super().step(action)
            return obs, 1.0, d, t, i

    env = ActionRepeat(RewEnv(n_steps=100), 4)
    env.reset()
    _, reward, *_ = env.step(0)
    assert reward == 4.0


def test_frame_stack_shapes_and_dilation():
    env = FrameStack(DiscreteDummyEnv(n_steps=100), num_stack=3, cnn_keys=["rgb"], dilation=2)
    obs, _ = env.reset()
    assert obs["rgb"].shape == (3, 3, 64, 64)
    for step in range(5):
        obs, *_ = env.step(0)
    # With dilation 2 the stacked frames are 2 steps apart.
    frames = obs["rgb"][:, 0, 0, 0].astype(int)
    assert frames[2] - frames[1] == 2


def test_actions_as_observation_discrete():
    env = ActionsAsObservationWrapper(DiscreteDummyEnv(action_dim=3, n_steps=100), num_stack=2, noop=0)
    obs, _ = env.reset()
    assert obs["action_stack"].shape == (6,)
    assert obs["action_stack"][0] == 1.0  # noop one-hot
    obs, *_ = env.step(2)
    assert obs["action_stack"][-1] == 1.0  # last action one-hot at idx 2


def test_actions_as_observation_continuous_noop():
    # scalar float noop broadcasts over the action vector (reference accepts a float)
    env = ActionsAsObservationWrapper(ContinuousDummyEnv(action_dim=2), num_stack=2, noop=0.0)
    obs, _ = env.reset()
    assert obs["action_stack"].shape == (4,)
    assert (obs["action_stack"] == 0.0).all()
    # a wrong-length list is still rejected
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(ContinuousDummyEnv(action_dim=2), num_stack=2, noop=[0.0, 0.0, 0.0])


def test_reward_as_observation():
    env = RewardAsObservationWrapper(DiscreteDummyEnv(n_steps=100))
    obs, _ = env.reset()
    assert "reward" in obs
    assert obs["reward"].shape == (1,)


def _pipeline_cfg(env_option, cnn=("rgb",), mlp=("state",), **env_overrides):
    overrides = ["exp=ppo_dummy", f"env={env_option}"]
    overrides.append("algo.cnn_keys.encoder=" + str(list(cnn)).replace("'", '"'))
    overrides.append("algo.mlp_keys.encoder=" + str(list(mlp)).replace("'", '"'))
    for k, v in env_overrides.items():
        overrides.append(f"env.{k}={v}")
    return compose(overrides=overrides)


@pytest.mark.parametrize("env_option", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"])
def test_make_env_pipeline_dict_obs(env_option):
    cfg = _pipeline_cfg(env_option)
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert obs["rgb"].shape == (3, 64, 64)
    assert obs["rgb"].dtype == np.uint8
    assert obs["state"].shape == (10,)
    env.close()


def test_make_env_grayscale_resize():
    cfg = _pipeline_cfg("discrete_dummy", grayscale=True, screen_size=32)
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert obs["rgb"].shape == (1, 32, 32)
    env.close()


def test_make_env_frame_stack():
    cfg = _pipeline_cfg("discrete_dummy", frame_stack=4)
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert obs["rgb"].shape == (4, 3, 64, 64)
    env.close()


def test_make_env_vector_only_gym():
    cfg = compose(overrides=["exp=ppo", "env.capture_video=False"])
    cfg.algo.mlp_keys.encoder = ["state"]
    cfg.algo.cnn_keys.encoder = []
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert list(obs.keys()) == ["state"]
    assert obs["state"].shape == (4,)
    env.close()


def test_make_env_unknown_keys_raise():
    cfg = _pipeline_cfg("discrete_dummy", cnn=("nope",), mlp=())
    with pytest.raises(ValueError):
        make_env(cfg, seed=0, rank=0)()


def test_restart_on_exception_marks_truncation():
    """A crashed+restarted env must surface as a truncation so training loops commit
    the episode boundary to the replay buffer (design note in the wrapper docstring)."""
    import gymnasium as gym
    import numpy as np

    from sheeprl_tpu.envs.wrappers import RestartOnException

    class Crashy(gym.Env):
        observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
        action_space = gym.spaces.Discrete(2)

        def __init__(self):
            self.steps = 0

        def reset(self, seed=None, options=None):
            return np.zeros(2, np.float32), {}

        def step(self, action):
            self.steps += 1
            if self.steps == 2:
                raise RuntimeError("env crashed")
            return np.zeros(2, np.float32), 0.0, False, False, {}

    env = RestartOnException(Crashy, maxfails=3, window=60.0)
    env.reset()
    env.step(0)
    obs, reward, terminated, truncated, info = env.step(0)  # crash -> restart
    assert truncated and not terminated
    assert info.get("restart_on_exception") is True
    # the rebuilt env keeps working
    obs, reward, terminated, truncated, info = env.step(0)
    assert not truncated and "restart_on_exception" not in info
