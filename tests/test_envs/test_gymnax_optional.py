"""The gymnax adapter's optional-dependency path: ``env.jax.env_id=gymnax:<Env>``
must fail with a clear ACTIONABLE message when gymnax is absent — not a bare
ImportError traceback from deep inside the adapter."""

import builtins
import sys

import pytest

from sheeprl_tpu.envs.jax import make_jax_env


@pytest.fixture()
def without_gymnax(monkeypatch):
    """Force the no-gymnax environment regardless of what the container has."""
    monkeypatch.delitem(sys.modules, "gymnax", raising=False)
    real_import = builtins.__import__

    def _import(name, *args, **kwargs):
        if name == "gymnax" or name.startswith("gymnax."):
            raise ImportError(f"No module named {name!r}")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", _import)


def test_gymnax_env_id_raises_actionable_error(without_gymnax):
    with pytest.raises(ImportError) as exc_info:
        make_jax_env("gymnax:CartPole-v1")
    msg = str(exc_info.value)
    # actionable: names the env id, the missing package, the fix, and the
    # in-tree alternatives that need no extra install
    assert "gymnax:CartPole-v1" in msg
    assert "pip install gymnax" in msg
    assert "cartpole" in msg and "pendulum" in msg


def test_gymnax_error_reaches_anakin_entry_gate(without_gymnax):
    """The Anakin engine's env builder surfaces the same actionable message (the
    config path a user actually hits: env.jax.env_id=gymnax:<Env>)."""
    from sheeprl_tpu.config.core import DotDict
    from sheeprl_tpu.engine.anakin import anakin_env

    cfg = DotDict.wrap(
        {"env": {"id": "x", "jax": {"enabled": True, "env_id": "gymnax:CartPole-v1"}}}
    )
    with pytest.raises(ImportError, match="pip install gymnax"):
        anakin_env(cfg)


def test_in_tree_jax_envs_never_touch_gymnax(without_gymnax):
    env = make_jax_env("jax_cartpole")
    assert env.default_params() is not None


def test_unknown_jax_env_id_lists_options():
    with pytest.raises(ValueError, match="gymnax:<EnvName>"):
        make_jax_env("not_a_real_env")
