"""Barrier timeouts: fail loudly instead of hanging when a peer host dies."""

from __future__ import annotations

import time

import pytest

from sheeprl_tpu.parallel.mesh import (
    BarrierTimeoutError,
    _wait_with_timeout,
    sync_global_devices_with_timeout,
)


def test_wait_with_timeout_raises_on_stall():
    with pytest.raises(BarrierTimeoutError, match="supervise"):
        _wait_with_timeout(lambda: time.sleep(5), "ckpt_sync", 0.2)


def test_wait_with_timeout_error_is_actionable():
    with pytest.raises(BarrierTimeoutError, match="SHEEPRL_TPU_BARRIER_TIMEOUT_S"):
        _wait_with_timeout(lambda: time.sleep(5), "ckpt_sync", 0.2)


def test_wait_with_timeout_fast_fn_passes():
    _wait_with_timeout(lambda: None, "noop", 5.0)


def test_wait_with_timeout_propagates_fn_error():
    def boom():
        raise RuntimeError("collective failed")

    with pytest.raises(RuntimeError, match="collective failed"):
        _wait_with_timeout(boom, "boom", 5.0)


def test_sync_is_noop_single_process():
    sync_global_devices_with_timeout("unit_test", timeout_s=0.1)
