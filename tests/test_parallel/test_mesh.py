"""Mesh/sharding substrate tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh


def test_virtual_device_count():
    assert len(jax.devices()) == 8


def test_build_mesh_shapes():
    mesh = build_mesh(data=-1)
    assert mesh.shape["data"] == 8
    mesh = build_mesh(data=4, model=2)
    assert mesh.shape["data"] == 4
    assert mesh.shape["model"] == 2
    with pytest.raises(ValueError):
        build_mesh(data=3, model=2)


def test_batch_sharding_and_replication():
    ctx = MeshContext(mesh=build_mesh())
    x = np.arange(16, dtype=np.float32).reshape(16, 1)
    sharded = jax.device_put(x, ctx.batch_sharding())
    assert len(sharded.sharding.device_set) == 8
    rep = ctx.replicate(jnp.ones(4))
    assert rep.sharding.is_fully_replicated


def test_data_parallel_grad_is_global_mean():
    """Loss mean over a sharded batch must produce the same grads as unsharded."""
    ctx = MeshContext(mesh=build_mesh())
    w = jnp.ones((4,))
    x = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)

    def loss(w, x):
        return ((x @ w) ** 2).mean()

    g_ref = jax.grad(loss)(w, jnp.asarray(x))
    x_sharded = jax.device_put(x, ctx.batch_sharding())
    w_rep = ctx.replicate(w)
    g_sharded = jax.jit(jax.grad(loss))(w_rep, x_sharded)
    assert np.allclose(np.asarray(g_ref), np.asarray(jax.device_get(g_sharded)), atol=1e-5)


def test_rng_chain_advances():
    ctx = MeshContext(mesh=build_mesh())
    k1, k2 = ctx.rng(), ctx.rng()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))


def test_precision_policy():
    ctx = MeshContext(mesh=build_mesh(), precision="bf16-mixed")
    assert ctx.compute_dtype == jnp.bfloat16
    assert ctx.param_dtype == jnp.float32
    ctx = MeshContext(mesh=build_mesh(), precision="32-true")
    assert ctx.compute_dtype == jnp.float32


def test_put_batch_replication_fallback_warns_once(caplog):
    """dp>1 with a non-dividing batch must warn (once): silent replication is a perf
    cliff — a multi-chip mesh scaling like one chip with no message (VERDICT r2 #5)."""
    import logging

    ctx = MeshContext(mesh=build_mesh())  # 8-way data mesh
    with caplog.at_level(logging.WARNING, logger="sheeprl_tpu.parallel.mesh"):
        ctx.put_batch({"x": np.zeros((3, 2), np.float32)})  # 3 % 8 != 0
        ctx.put_batch({"x": np.zeros((5, 2), np.float32)})
    warnings = [r for r in caplog.records if "REPLICATED" in r.message]
    assert len(warnings) == 1  # once per run, not per call

    caplog.clear()
    ctx2 = MeshContext(mesh=build_mesh())
    with caplog.at_level(logging.WARNING, logger="sheeprl_tpu.parallel.mesh"):
        out = ctx2.put_batch({"x": np.zeros((16, 2), np.float32)})
    assert not [r for r in caplog.records if "REPLICATED" in r.message]
    assert "data" in str(out["x"].sharding.spec)  # actually sharded
