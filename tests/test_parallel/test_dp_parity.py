"""Data-parallel numerics: a DV3 train step on an 8-way-sharded batch must match the
replicated (single-layout) result — the TPU analogue of the reference's LT_DEVICES=2
DDP-vs-1-device equivalence (SURVEY §4)."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config.core import compose
from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh


@pytest.fixture(scope="module")
def dv3_setup():
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments

    cfg = compose(overrides=["exp=dreamer_v3_dummy"])
    cfg.algo.cnn_keys.encoder = ["rgb"]
    cfg.algo.mlp_keys.encoder = []
    size = cfg.env.screen_size
    # fp32 end to end: this is a numerics test, not a precision test.
    ctx = MeshContext(mesh=build_mesh(data=8), precision="32-true", seed=0)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, size, size), np.uint8)})
    actions_dim = (4,)
    world_model, actor, critic, params, _ = build_agent(ctx, actions_dim, False, cfg, obs_space)
    train_step, init_opt_states = make_train_step(
        world_model, actor, critic, cfg, ["rgb"], [], {"rgb": (3, size, size)}
    )
    opt_states = ctx.replicate(init_opt_states(params))
    moments = ctx.replicate(init_moments())

    T, B = 6, 8
    rng = np.random.default_rng(0)
    data = {
        "rgb": rng.integers(0, 255, (T, B, 3, size, size), dtype=np.uint8),
        "actions": rng.random((T, B, int(sum(actions_dim)))).astype(np.float32),
        "rewards": rng.random((T, B, 1)).astype(np.float32),
        "terminated": np.zeros((T, B, 1), np.float32),
        "is_first": np.zeros((T, B, 1), np.float32),
    }
    return ctx, params, opt_states, moments, train_step, data


def _run(ctx, params, opt_states, moments, train_step, data, sharding):
    placed = {k: jax.device_put(v, sharding) for k, v in data.items()}
    train_jit = jax.jit(train_step)
    new_params, _, _, metrics = train_jit(
        params, opt_states, moments, placed, jax.random.PRNGKey(7), jnp.asarray(True)
    )
    return jax.device_get(new_params), jax.device_get(metrics)


def test_dv3_sharded_batch_matches_replicated(dv3_setup):
    ctx, params, opt_states, moments, train_step, data = dv3_setup
    assert ctx.data_parallel_size == 8
    p_rep, m_rep = _run(ctx, params, opt_states, moments, train_step, data, ctx.replicated)
    p_sh, m_sh = _run(ctx, params, opt_states, moments, train_step, data, ctx.sharding(None, "data"))
    for k in m_rep:
        np.testing.assert_allclose(m_rep[k], m_sh[k], rtol=2e-4, atol=2e-5, err_msg=k)
    flat_rep = jax.tree.leaves(p_rep)
    flat_sh = jax.tree.leaves(p_sh)
    # Sharded reductions reorder float sums; allow tiny absolute noise.
    for a, b in zip(flat_rep, flat_sh):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4)


def test_put_batch_shards_divisible_axis():
    ctx = MeshContext(mesh=build_mesh(data=8), precision="32-true", seed=0)
    tree = {"a": np.zeros((16, 3)), "b": np.zeros((7, 2))}  # 7 not divisible -> replicated
    out = ctx.put_batch(tree, batch_axis=0)
    assert out["a"].sharding.spec == jax.sharding.PartitionSpec("data")
    assert out["b"].sharding.spec == jax.sharding.PartitionSpec()
