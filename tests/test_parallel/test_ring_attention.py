"""Ring attention over the `sequence` mesh axis: exact parity with full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.ring_attention import make_ring_attention, reference_attention
from sheeprl_tpu.parallel.mesh import build_mesh


def _qkv(B=2, T=64, H=2, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("ring", [4, 8])
def test_ring_attention_matches_full_attention(causal, ring):
    devices = jax.devices()
    assert len(devices) >= ring
    mesh = build_mesh(data=1, model=1, sequence=ring, devices=devices[:ring])
    q, k, v = _qkv()
    ring_fn = jax.jit(make_ring_attention(mesh, causal=causal))
    out = ring_fn(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_gradients_match():
    mesh = build_mesh(data=1, model=1, sequence=4, devices=jax.devices()[:4])
    q, k, v = _qkv(T=32)
    ring_fn = make_ring_attention(mesh, causal=True)

    g_ring = jax.grad(lambda q, k, v: jnp.sum(ring_fn(q, k, v) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(reference_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(
        q, k, v
    )
    for gr, gf, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), atol=5e-5, err_msg=name)
