"""Ring attention over the `sequence` mesh axis: exact parity with full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.ring_attention import make_ring_attention, reference_attention
from sheeprl_tpu.parallel.mesh import build_mesh


def _qkv(B=2, T=64, H=2, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("ring", [4, 8])
def test_ring_attention_matches_full_attention(causal, ring):
    devices = jax.devices()
    assert len(devices) >= ring
    mesh = build_mesh(data=1, model=1, sequence=ring, devices=devices[:ring])
    q, k, v = _qkv()
    ring_fn = jax.jit(make_ring_attention(mesh, causal=causal))
    out = ring_fn(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_gradients_match():
    mesh = build_mesh(data=1, model=1, sequence=4, devices=jax.devices()[:4])
    q, k, v = _qkv(T=32)
    ring_fn = make_ring_attention(mesh, causal=True)

    g_ring = jax.grad(lambda q, k, v: jnp.sum(ring_fn(q, k, v) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(reference_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(
        q, k, v
    )
    for gr, gf, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), atol=5e-5, err_msg=name)


@pytest.mark.parametrize("window", [None, 3])
def test_ring_attention_segments_and_window_match(window):
    """Segment (episode-boundary) and sliding-window masks must agree with the
    dense oracle — the masks the attention policy variant relies on."""
    mesh = build_mesh(data=1, sequence=8)
    rng = np.random.default_rng(3)
    B, T, H, D = 2, 32, 2, 8
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32)) for _ in range(3))
    segs = jnp.asarray(np.sort(rng.integers(0, 4, (B, T)), axis=-1).astype(np.int32))
    ring_fn = jax.jit(make_ring_attention(mesh, causal=True, window=window))
    out = ring_fn(q, k, v, segs)
    ref = reference_attention(q, k, v, causal=True, segment_ids=segs, window=window)
    assert np.max(np.abs(np.asarray(out) - np.asarray(ref))) < 1e-5
