"""Multi-host path executed for real: 2 JAX processes over the gloo CPU transport
(the analogue of the reference's LT_DEVICES=2 localhost DDP tests, SURVEY §4).

Covers the three multi-host mechanisms VERDICT r1 flagged as never executed:
``MeshContext.broadcast_obj``/``barrier``, the ``RankIndependentMetricAggregator``
cross-rank gather, and the ``CheckpointManager`` barrier-synced per-rank buffer shards.
"""

import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

# Each test spawns 2 JAX processes that re-compile everything — slow tier.
pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[2]

CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["SHEEPRL_TPU_QUIET"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, pid, tmp = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(coordinator_address=coordinator, num_processes=2, process_id=pid)
    assert jax.process_count() == 2

    sys.path.insert(0, {repo!r})
    import numpy as np
    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh
    from sheeprl_tpu.utils.metric import RankIndependentMetricAggregator

    # 1. mesh over all 4 global devices + host-object broadcast
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()), precision="fp32", seed=0)
    assert ctx.world_size == 4 and ctx.data_parallel_size == 4
    value = ctx.broadcast_obj(np.asarray([100 + pid]))
    assert int(np.asarray(value)[0]) == 100, value  # everyone sees rank 0's payload
    ctx.barrier()

    # 2. rank-independent metrics: each rank reports its own value; compute() gathers
    agg = RankIndependentMetricAggregator()
    agg.keep(["Loss/a", "Rewards/rew_avg"])
    agg.update("Loss/a", float(pid + 1))
    if pid == 0:  # rank-dependent lazy key — must NOT break the fixed-shape gather
        agg.update("Rewards/rew_avg", 7.0)
    per_rank = agg.compute_per_rank()
    assert per_rank["Loss/a"].tolist() == [1.0, 2.0], per_rank
    mean = agg.compute()
    assert mean["Loss/a"] == 1.5 and mean["Rewards/rew_avg"] == 7.0, mean

    # 3. checkpoint: per-rank buffer shards via the barrier-synced protocol
    mgr = CheckpointManager(os.path.join(tmp, "ckpts"), keep_last=2)
    state = {{"params": {{"w": jax.numpy.ones((2, 2))}}, "iter_num": 3, "rb": {{"rank_data": pid * 10}}}}
    out = mgr.save(7, state)
    ctx.barrier()
    loaded = CheckpointManager.load(out, templates={{"params": {{"w": np.zeros((2, 2))}}}})
    assert loaded["iter_num"] == 3
    assert loaded["rb"]["rank_data"] == pid * 10, (pid, loaded["rb"])  # own shard restored
    print(f"child {{pid}} OK", flush=True)
    """
).format(repo=str(REPO))


def _run_two_children(script_text, tmp_path, timeout, ok_marker):
    """Launch the child script as 2 coordinated JAX processes; assert both exit 0
    and print their ``<ok_marker> <pid> OK`` line. Returns the child outputs."""
    script = tmp_path / "child.py"
    script.write_text(script_text)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(pid), str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outputs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                # One child died and its sibling is stuck in a collective: reap both
                # so we can show the FAILED child's diagnostics instead of a timeout.
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                out, _ = p.communicate()
            outputs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"{ok_marker} {pid} failed:\n{out[-3000:]}"
        assert f"{ok_marker} {pid} OK" in out
    return outputs


def test_two_process_multihost(tmp_path):
    _run_two_children(CHILD, tmp_path, timeout=300, ok_marker="child")


# Per-rank param fingerprint at every checkpoint: the r2 multihost RNG bug's failure
# mode was SILENT replica divergence — liveness checks (ckpt exists, events exist)
# would still pass.  Each rank hashes the params object IT passes to
# CheckpointManager.save; the parent asserts the ranks' hashes are bit-identical.
HASH_CAPTURE = textwrap.dedent(
    """
    import hashlib
    import numpy as _np
    from sheeprl_tpu.checkpoint import manager as _mgr

    _orig_save = _mgr.CheckpointManager.save

    def _capture_save(self, step, state):
        flat, _ = jax.tree.flatten(jax.device_get(state["params"]))
        h = hashlib.sha256()
        for a in flat:
            h.update(_np.ascontiguousarray(a).tobytes())
        with open(f"{tmp}/params_hash_rank{pid}_step{step}.txt", "w") as f:
            f.write(h.hexdigest())
        return _orig_save(self, step, state)

    _mgr.CheckpointManager.save = _capture_save
    """
)


def _assert_rank_params_identical(tmp_path):
    """Pair up the per-rank hash files by step and require bit-identical params."""
    hashes = {}
    for f in tmp_path.glob("params_hash_rank*_step*.txt"):
        rank, step = f.stem.replace("params_hash_rank", "").split("_step")
        hashes.setdefault(step, {})[rank] = f.read_text()
    assert hashes, "no per-rank param hashes captured"
    for step, by_rank in hashes.items():
        assert len(by_rank) == 2, f"step {step}: only ranks {list(by_rank)} hashed"
        assert by_rank["0"] == by_rank["1"], (
            f"step {step}: per-rank params DIVERGED (rank0 {by_rank['0'][:12]}… != "
            f"rank1 {by_rank['1'][:12]}…) — the SPMD replicas are no longer identical"
        )


TRAIN_CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["SHEEPRL_TPU_QUIET"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, pid, tmp = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, {repo!r})
    from sheeprl_tpu.cli import run

    HASH_CAPTURE

    run([
        "exp=dreamer_v3_dummy",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.total_steps=64",
        "algo.learning_starts=32",
        "algo.run_test=False",
        "buffer.memmap=False",
        "buffer.checkpoint=True",
        "checkpoint.every=32",
        "metric.log_every=16",
        f"log_root={{tmp}}/logs",
        f"run_name=shared",
        f"mesh.distributed.coordinator_address={{coordinator}}",
        "mesh.distributed.num_processes=2",
        f"mesh.distributed.process_id={{pid}}",
    ])
    print(f"train child {{pid}} OK", flush=True)
    """
).format(repo=str(REPO)).replace("HASH_CAPTURE", HASH_CAPTURE)


def test_two_process_dreamer_v3_training(tmp_path):
    """FULL DreamerV3 training over 2 JAX processes x 2 local CPU devices (the
    reference's LT_DEVICES=2 equivalent, end-to-end): batch sharded over the global
    data axis, GSPMD gradient all-reduce across processes, rank-0 logging, per-rank
    buffer checkpoint shards."""
    _run_two_children(TRAIN_CHILD, tmp_path, timeout=540, ok_marker="train child")
    ckpts = sorted((tmp_path / "logs").rglob("ckpt_*"))
    assert ckpts, "no checkpoint written by the 2-process run"
    events = sorted((tmp_path / "logs").rglob("events.out.tfevents.*"))
    assert events, "rank 0 wrote no tensorboard events"
    _assert_rank_params_identical(tmp_path)


DECOUPLED_CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["SHEEPRL_TPU_QUIET"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, pid, tmp = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, {repo!r})
    from sheeprl_tpu.cli import run

    HASH_CAPTURE

    run([
        "exp=ppo_decoupled",
        "env=discrete_dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.total_steps=128",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.run_test=False",
        "buffer.memmap=False",
        "checkpoint.every=64",
        "metric.log_every=16",
        f"log_root={{tmp}}/logs",
        f"run_name=shared",
        f"mesh.distributed.coordinator_address={{coordinator}}",
        "mesh.distributed.num_processes=2",
        f"mesh.distributed.process_id={{pid}}",
    ])
    print(f"decoupled child {{pid}} OK", flush=True)
    """
).format(repo=str(REPO)).replace("HASH_CAPTURE", HASH_CAPTURE)


def test_two_process_ppo_decoupled(tmp_path):
    """The decoupled player/learner thread split under jax.process_count()==2 (the
    reference's decoupled mode is inherently multi-rank, ppo_decoupled.py:368-620):
    each process runs its own player thread; the learner's jitted update spans the
    global 2x2-device mesh, so the gradient reduce crosses processes via GSPMD."""
    _run_two_children(DECOUPLED_CHILD, tmp_path, timeout=540, ok_marker="decoupled child")
    ckpts = sorted((tmp_path / "logs").rglob("ckpt_*"))
    assert ckpts, "no checkpoint written by the 2-process decoupled run"
    events = sorted((tmp_path / "logs").rglob("events.out.tfevents.*"))
    assert events, "rank 0 wrote no tensorboard events"
    _assert_rank_params_identical(tmp_path)


MIRROR_CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["SHEEPRL_TPU_QUIET"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, pid, tmp = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(coordinator_address=coordinator, num_processes=2, process_id=pid)
    sys.path.insert(0, {repo!r})

    import numpy as np
    import jax.numpy as jnp
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
    from sheeprl_tpu.data.device_buffer import (
        MultiProcessDeviceReplayMirror,
        sample_index_block,
    )
    from sheeprl_tpu.parallel.mesh import build_mesh

    # 2 processes x 2 local devices -> global data axis of 4.  Each process owns
    # 4 LOCAL envs; rows and terminal-add cadence DIVERGE by process on purpose.
    mesh = build_mesh(devices=jax.devices())
    n_envs, cap, seq, batch = 4, 16, 4, 8
    specs = {{"rgb": ((3, 8, 8), jnp.uint8), "rewards": ((1,), jnp.float32)}}
    rb = EnvIndependentReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer)
    rb.seed(100 + pid)
    mirror = MultiProcessDeviceReplayMirror(cap, n_envs, specs, global_mesh=mesh)
    assert mirror.local_dp == 2 and mirror.global_envs == 8 and mirror.env_offset == 4 * pid

    rng = np.random.default_rng(10 + pid)
    def row(t, envs=n_envs):
        return {{
            "rgb": rng.integers(0, 255, (1, envs, 3, 8, 8), dtype=np.uint8),
            "rewards": np.full((1, envs, 1), float(1000 * pid + t), np.float32),
        }}

    for t in range(25):  # wraps the ring
        r = row(t)
        positions = [rb.buffer[e]._pos for e in range(n_envs)]
        mirror.add(r, list(range(n_envs)), positions)
        rb.add(r)
        # process-DIVERGENT terminal adds: only rank pid's cadence fires — local
        # scatters must not require the sibling process to participate
        if t % (5 + pid) == 2:
            sub = {{k: v[:, :1] for k, v in row(100 + t, 1).items()}}
            env_sel = 1 + pid
            mirror.add(sub, [env_sel], [rb.buffer[env_sel]._pos])
            rb.add(sub, indices=[env_sel])

    # local ring content == local host buffer content
    for k in ("rgb", "rewards"):
        dev = mirror.host_rows(k)
        for e in range(n_envs):
            host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *dev.shape[2:])
            np.testing.assert_array_equal(dev[:, e], host, err_msg=f"{{k}} env {{e}}")

    # per-process sampling (local shards) -> global batch-sharded index arrays ->
    # ONE SPMD gather all processes dispatch in lockstep
    envs, starts = sample_index_block(rb, batch, seq, n=2, dp=mirror.local_dp)
    ge, gs = mirror.globalize_indices(
        np.ascontiguousarray(envs, np.int32), np.ascontiguousarray(starts, np.int32)
    )
    gather = jax.jit(mirror.make_gather_fn(seq))
    out0 = None
    for g in range(2):
        out = gather(mirror.global_view(), ge[g], gs[g])
        if g == 0:
            out0 = out
        # each process verifies ITS addressable batch columns against ITS host rows
        for k in ("rgb", "rewards"):
            arr = out[k]
            assert arr.shape[1] == 16  # global batch = world x batch
            for shard in arr.addressable_shards:
                sl = shard.index[1]
                data = np.asarray(shard.data)
                for col, b_global in enumerate(range(sl.start, sl.stop)):
                    b_local = b_global - pid * batch
                    assert 0 <= b_local < batch, (pid, b_global)
                    e, st = int(envs[g, b_local]), int(starts[g, b_local])
                    host = np.asarray(rb.buffer[e]._buf[k])[:, 0].reshape(cap, *data.shape[2:])
                    expect = np.stack([host[(st + i) % cap] for i in range(seq)])
                    np.testing.assert_array_equal(data[:, col], expect, err_msg=f"{{k}} b={{b_global}}")

    # resume path: a FRESH MP mirror rebuilt from the host buffer must hold the
    # same local rows (each process restores its own shard independently)
    rebuilt = MultiProcessDeviceReplayMirror(cap, n_envs, specs, global_mesh=mesh)
    rebuilt.load_from(rb)
    for k in ("rgb", "rewards"):
        np.testing.assert_array_equal(rebuilt.host_rows(k), mirror.host_rows(k), err_msg=f"load_from {{k}}")
    out2 = gather(rebuilt.global_view(), ge[0], gs[0])
    for k in ("rgb", "rewards"):
        for s_new, s_old in zip(out2[k].addressable_shards, out0[k].addressable_shards):
            np.testing.assert_array_equal(np.asarray(s_new.data), np.asarray(s_old.data), err_msg=f"resume gather {{k}}")
    print(f"mirror child {{pid}} OK", flush=True)
    """
).format(repo=str(REPO))


def test_two_process_device_mirror_parity(tmp_path):
    """Multi-process device replay ≡ host replay (VERDICT r4 #3): per-process local
    rings with process-divergent writes, per-process index sampling, zero-copy
    global view + lockstep SPMD gather — every gathered element must equal the
    owning process's host-buffer rows."""
    _run_two_children(MIRROR_CHILD, tmp_path, timeout=300, ok_marker="mirror child")


DEVICE_TRAIN_CHILD = TRAIN_CHILD.replace(
    '"buffer.memmap=False",',
    '"buffer.memmap=False",\n        "buffer.device=True",\n        "env.num_envs=2",',
).replace('print(f"train child {pid} OK", flush=True)', 'print(f"device train child {pid} OK", flush=True)')


def test_two_process_dreamer_v3_device_replay_training(tmp_path):
    """FULL DreamerV3 training over 2 processes WITH the device-replay fast path
    (the r4 gate removed): the HBM mirror must not fall back, and the per-rank
    params must stay bit-identical through training."""
    outputs = _run_two_children(DEVICE_TRAIN_CHILD, tmp_path, timeout=540, ok_marker="device train child")
    for out in outputs:
        assert "falling back to host-side sampling" not in out, out[-2000:]
    _assert_rank_params_identical(tmp_path)


SAC_CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["SHEEPRL_TPU_QUIET"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, pid, tmp = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, {repo!r})
    from sheeprl_tpu.cli import run

    HASH_CAPTURE

    run([
        "exp=sac",
        "env=continuous_dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.total_steps=96",
        "algo.learning_starts=32",
        "algo.replay_ratio=0.5",
        "algo.per_rank_batch_size=16",
        "algo.hidden_size=8",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.run_test=False",
        "buffer.size=4096",
        "buffer.memmap=False",
        "buffer.checkpoint=True",
        "checkpoint.every=48",
        "metric.log_every=16",
        f"log_root={{tmp}}/logs",
        f"run_name=shared",
        f"mesh.distributed.coordinator_address={{coordinator}}",
        "mesh.distributed.num_processes=2",
        f"mesh.distributed.process_id={{pid}}",
    ])
    print(f"sac child {{pid}} OK", flush=True)
    """
).format(repo=str(REPO)).replace("HASH_CAPTURE", HASH_CAPTURE)


def test_two_process_sac_training(tmp_path):
    """Off-policy multi-host coverage (VERDICT r2 item 4): SAC over 2 JAX
    processes — the [G, B] training block's batch axis is sharded over the global
    data axis, so the critic/actor/alpha gradient means reduce across processes;
    per-rank replay shards land in the checkpoint."""
    _run_two_children(SAC_CHILD, tmp_path, timeout=540, ok_marker="sac child")
    ckpts = sorted((tmp_path / "logs").rglob("ckpt_*"))
    assert ckpts, "no checkpoint written by the 2-process SAC run"
    events = sorted((tmp_path / "logs").rglob("events.out.tfevents.*"))
    assert events, "rank 0 wrote no tensorboard events"
    _assert_rank_params_identical(tmp_path)
