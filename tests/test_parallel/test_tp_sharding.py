"""Tensor parallelism over the `model` mesh axis: params column-sharded, jitted
train step numerically equal to the replicated run (GSPMD-propagated)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh


def _mlp_apply(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    h = jnp.tanh(h @ params["w2"] + params["b2"])
    return h @ params["w3"]


def _loss(params, x, y):
    return jnp.mean((_mlp_apply(params, x) - y) ** 2)


def _params():
    rng = np.random.default_rng(0)
    return {
        "w1": jnp.asarray(rng.normal(0, 0.1, (16, 256)).astype(np.float32)),
        "b1": jnp.zeros(256),
        "w2": jnp.asarray(rng.normal(0, 0.1, (256, 256)).astype(np.float32)),
        "b2": jnp.zeros(256),
        "w3": jnp.asarray(rng.normal(0, 0.1, (256, 4)).astype(np.float32)),
    }


def test_tp_sharded_step_matches_replicated():
    devices = jax.devices()
    assert len(devices) >= 8
    tp_ctx = MeshContext(mesh=build_mesh(data=4, model=2, devices=devices[:8]), precision="fp32")
    rep_ctx = MeshContext(mesh=build_mesh(data=8, model=1, devices=devices[:8]), precision="fp32")

    opt = optax.adam(1e-2)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(32, 16)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(32, 4)).astype(np.float32))

    @jax.jit
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(_loss)(params, x, y)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    results = {}
    for name, ctx in (("tp", tp_ctx), ("rep", rep_ctx)):
        params = ctx.shard_params(_params()) if name == "tp" else ctx.replicate(_params())
        if name == "tp":
            # the big kernels must actually be sharded over the model axis
            spec = params["w2"].sharding.spec
            assert spec[-1] == "model", spec
            assert params["b1"].sharding.spec == (), "biases stay replicated"
        opt_state = opt.init(params)
        xb = ctx.put_batch(x)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, xb, y)
        results[name] = (jax.device_get(params), float(loss))

    np.testing.assert_allclose(results["tp"][1], results["rep"][1], rtol=1e-5)
    for k in results["rep"][0]:
        np.testing.assert_allclose(
            np.asarray(results["tp"][0][k]), np.asarray(results["rep"][0][k]), atol=1e-5, err_msg=k
        )
